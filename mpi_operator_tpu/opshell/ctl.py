"""tpujobctl: the kubectl-equivalent operations CLI.

The reference's entire day-2 surface is kubectl against the CRD —
`kubectl create -f pi.yaml`, `kubectl get mpijobs`, `kubectl describe
mpijob pi`, `kubectl delete mpijob pi` (/root/reference/examples/pi/
README.md; the Events section of describe is the audit log the controller
writes, SURVEY.md §5.5). This framework owns its store, so it ships the
equivalent verbs against any backend:

  python -m mpi_operator_tpu.opshell.ctl --store sqlite:/var/lib/tpujob/store.db get
  python -m mpi_operator_tpu.opshell.ctl --store http://store:8475 create -f job.yaml
  python -m mpi_operator_tpu.opshell.ctl --store ... describe myjob
  python -m mpi_operator_tpu.opshell.ctl --store ... watch myjob

Verbs: create (strict-schema admission), get (table or -o json), describe
(spec summary + per-replica status + pods + the Event audit trail), delete,
events, logs (a pod's stdout/stderr from the executor's log dir — the path
is stamped in pod.status.log_path and is local to the node in
spec.node_name), scale (live worker-replica change — the elastic entry
point), suspend/resume (runPolicy.suspend), watch (stream condition
transitions until the job finishes, riding the store watch protocol),
nodes (the registered agent fleet, ≙ kubectl get nodes), cordon/uncordon/
drain (node lifecycle: hold new bindings; evict for maintenance), store
status (replica-set roles/lease/lag, ≙ etcdctl endpoint status; exits
nonzero when the set has no leader).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, List, Optional

from mpi_operator_tpu.api.client import TPUJobClient, ValidationRejected
from mpi_operator_tpu.api.conditions import (
    is_failed,
    is_finished,
    is_succeeded,
)
from mpi_operator_tpu.api.schema import ManifestError
from mpi_operator_tpu.machinery.store import (
    AlreadyExists,
    Conflict,
    Forbidden,
    NotFound,
    Unauthorized,
)


def job_state(job: Any) -> str:
    """One-word state column, precedence mirroring the condition machine
    (api/conditions.py; ≙ the STATE kubectl prints from status)."""
    s = job.status
    if is_succeeded(s):
        return "Succeeded"
    if is_failed(s):
        return "Failed"
    for cond in s.conditions:
        if cond.type == "Restarting" and cond.status:
            return "Restarting"
        if cond.type == "Suspended" and cond.status:
            return "Suspended"
    for cond in s.conditions:
        if cond.type == "Running" and cond.status:
            return "Running"
    if s.conditions:
        return "Created"
    return "Pending"


def _age(ts: Optional[float]) -> str:
    if not ts:
        return "-"
    d = max(0, int(time.time() - ts))
    if d < 120:
        return f"{d}s"
    if d < 7200:
        return f"{d // 60}m"
    return f"{d // 3600}h"


def _table(rows: List[List[str]], header: List[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [fmt.format(*header)]
    out += [fmt.format(*(str(c) for c in r)) for r in rows]
    return "\n".join(out)


def cmd_create(client: TPUJobClient, args) -> int:
    import yaml

    try:
        with open(args.filename) as f:
            doc = yaml.safe_load(f)
    except (OSError, yaml.YAMLError) as e:
        print(f"error: {args.filename}: {e}", file=sys.stderr)
        return 1
    try:
        job = client.create(doc)
    except (ManifestError, ValidationRejected, AlreadyExists) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"tpujob.tpujob.dev/{job.metadata.name} created")
    return 0


def cmd_get(client: TPUJobClient, args) -> int:
    if args.name:
        try:
            jobs = [client.get(args.name)]
        except NotFound as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    else:
        jobs = client.list()
    if args.output == "json":
        docs = [j.to_dict() for j in jobs]
        print(json.dumps(docs[0] if args.name else docs, indent=2))
        return 0
    if not jobs:
        print("No tpujobs found.")
        return 0
    from mpi_operator_tpu.api.defaults import set_defaults

    def _workers(j) -> int:
        # stored specs are deliberately un-defaulted: render the effective
        # replica count the controller will run with, not 'None'
        if j.spec.worker and j.spec.worker.replicas is not None:
            return j.spec.worker.replicas
        d = set_defaults(j.deepcopy())
        return d.spec.worker.replicas if d.spec.worker else 0

    rows = [
        [
            j.metadata.name,
            _workers(j),
            job_state(j),
            _age(j.metadata.creation_timestamp),
        ]
        for j in jobs
    ]
    print(_table(rows, ["NAME", "WORKERS", "STATE", "AGE"]))
    return 0


def cmd_delete(client: TPUJobClient, args) -> int:
    try:
        client.delete(args.name)
    except NotFound as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"tpujob.tpujob.dev/{args.name} deleted")
    return 0


def _job_events(store, job) -> List[Any]:
    evs = [
        e
        for e in store.list("Event", job.metadata.namespace)
        if e.involved.kind == "TPUJob" and e.involved.name == job.metadata.name
    ]
    evs.sort(key=lambda e: e.timestamp)
    return evs


def cmd_events(client: TPUJobClient, args) -> int:
    try:
        job = client.get(args.name)
    except NotFound as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    evs = _job_events(client.store, job)
    if not evs:
        print("No events.")
        return 0
    rows = [[_age(e.timestamp), e.type, e.reason, e.message] for e in evs]
    print(_table(rows, ["AGE", "TYPE", "REASON", "MESSAGE"]))
    # oscillation smell: the recorder dedupes identical (reason, message)
    # pairs, so a reason repeating with DIFFERENT messages means some
    # controller keeps re-deciding — the exact churn the convergence
    # checker reproduces offline (README: "Convergence checking")
    churn = {}
    for e in evs:
        churn.setdefault(e.reason, set()).add(e.message)
    noisy = sorted(r for r, msgs in churn.items() if len(msgs) >= 5)
    if noisy:
        print(
            f"note: reason(s) {', '.join(noisy)} repeat with varying "
            "messages — controllers may be oscillating; reproduce with "
            "`python -m mpi_operator_tpu.analysis converge`",
            file=sys.stderr,
        )
    return 0


def cmd_describe(client: TPUJobClient, args) -> int:
    try:
        job = client.get(args.name)
    except NotFound as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    m, s = job.metadata, job.spec
    lines = [
        f"Name:       {m.name}",
        f"Namespace:  {m.namespace}",
        f"UID:        {m.uid}",
        f"Created:    {_age(m.creation_timestamp)} ago",
        f"State:      {job_state(job)}",
    ]
    if s.slice:
        topo = f", topology {s.slice.topology}" if s.slice.topology else ""
        lines.append(
            f"Slice:      {s.slice.accelerator}"
            f" x{s.slice.chips_per_host}/host{topo}"
        )
    if s.worker:
        lines.append(f"Workers:    {s.worker.replicas}")
    for rtype, rs in sorted(job.status.replica_statuses.items()):
        lines.append(
            f"Replicas[{rtype}]: active={rs.active} "
            f"succeeded={rs.succeeded} failed={rs.failed}"
        )
    lines.append("Conditions:")
    for c in job.status.conditions:
        lines.append(
            f"  {c.type:<12} {str(bool(c.status)):<6} {c.reason} — {c.message}"
        )
    pods = client.store.list(
        "Pod", m.namespace, selector={"tpujob.dev/job-name": m.name}
    )
    if pods:
        lines.append("Pods:")
        for p in sorted(pods, key=lambda p: p.metadata.name):
            where = f" on {p.spec.node_name}" if p.spec.node_name else ""
            lines.append(
                f"  {p.metadata.name:<28} {p.status.phase}{where}"
            )
    evs = _job_events(client.store, job)
    lines.append("Events:")
    for e in evs or []:
        lines.append(f"  {_age(e.timestamp):<5} {e.type:<8} {e.reason:<22} {e.message}")
    if not evs:
        lines.append("  <none>")
    print("\n".join(lines))
    return 0


def _mutate_spec(client: TPUJobClient, name: str, mutate, done_msg: str) -> int:
    """Optimistic read-mutate-update with conflict retry + backoff
    (≙ kubectl's RetryOnConflict: the controller may be writing status
    concurrently). Admission validation lives in TPUJobClient.update — one
    admission path for create and mutate. Deliberately NOT a merge-patch:
    admission (validate_tpujob) must see the whole mutated spec, and a
    patch would bypass it server-side."""
    for attempt in range(5):
        try:
            job = client.get(name)
        except NotFound as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        mutate(job)
        try:
            # oplint: disable=RMW001 — whole-spec admission validation is the
            # point; the Conflict retry above is the blessed fallback shape
            client.update(job)
        except ValidationRejected as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        except Conflict:
            time.sleep(0.05 * (attempt + 1))
            continue  # re-read and re-apply
        except NotFound as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(done_msg)
        return 0
    print(f"error: persistent update conflict on {name}", file=sys.stderr)
    return 1


def cmd_scale(client: TPUJobClient, args) -> int:
    """≙ kubectl scale — the elastic entry point: the controller observes
    the replica change on the live job, republishes the projected host
    list, and drives a gang-coherent restart at the new size."""

    def mutate(job):
        job.spec.worker.replicas = args.replicas

    return _mutate_spec(
        client, args.name, mutate,
        f"tpujob.tpujob.dev/{args.name} scaled to {args.replicas} workers",
    )


def cmd_suspend(client: TPUJobClient, args) -> int:
    """≙ kubectl patch runPolicy.suspend=true (implemented here, unlike the
    reference's declared-only RunPolicy — SURVEY.md §2.2)."""

    def mutate(job):
        job.spec.run_policy.suspend = True

    return _mutate_spec(
        client, args.name, mutate, f"tpujob.tpujob.dev/{args.name} suspended"
    )


def cmd_resume(client: TPUJobClient, args) -> int:
    def mutate(job):
        job.spec.run_policy.suspend = False

    return _mutate_spec(
        client, args.name, mutate, f"tpujob.tpujob.dev/{args.name} resumed"
    )


def log_token_for(path: str, *, admin: Optional[str],
                  read: Optional[str]) -> Optional[str]:
    """THE credential policy for pod log fetches (cmd_logs and the follow
    loop both ride it so the two can never diverge): the READ token is the
    least-privileged credential that satisfies an agent log endpoint, so it
    is always preferred. The ADMIN token — full mutation rights on the
    store — is presented over TLS only: agent log endpoints are plain HTTP
    by default, and a bearer header on that seam is harvestable by anyone
    on the path (the VERDICT's credential-leak finding). A plaintext URL
    with only an admin token in hand gets NO credential — the fetch fails
    closed with a 401 and a hint, instead of leaking the cluster key."""
    if read:
        return read
    if admin and path.startswith("https://"):
        return admin
    return None


def cmd_logs(client: TPUJobClient, args) -> int:
    """≙ `kubectl logs pi-launcher` (the reference README's way to read the
    job's output). Accepts a pod name, or a job name (coordinator pod —
    worker 0 — by convention, since only it prints in SPMD workloads).
    Reads the file the executor stamped into pod.status.log_path; that path
    is local to the node in spec.node_name."""
    pod = client.store.try_get("Pod", client.namespace, args.name)
    if pod is None:
        pods = client.store.list(
            "Pod", client.namespace, selector={"tpujob.dev/job-name": args.name}
        )
        if not pods:
            print(f"error: no pod or job named {args.name!r}", file=sys.stderr)
            return 1
        pod = sorted(pods, key=lambda p: p.metadata.name)[0]
    path = pod.status.log_path
    if not path:
        print(
            f"error: pod {pod.metadata.name} has no logs recorded "
            f"(phase {pod.status.phase})",
            file=sys.stderr,
        )
        return 1
    if args.stderr:
        path = path[: -len(".log")] + ".err" if path.endswith(".log") else path
    admin = getattr(args, "log_admin_token", None)
    read = getattr(args, "log_read_token", None)
    token = log_token_for(path, admin=admin, read=read)
    if token is None and admin and path.startswith("http://"):
        print(
            "warning: refusing to send the admin token over plain HTTP to "
            f"{path.split('/logs/')[0]}/logs; pass --read-token-file (the "
            "downscoped log credential) or serve logs over TLS",
            file=sys.stderr,
        )
    if getattr(args, "follow", False):
        return _follow_logs(client, pod, path, token=token)
    try:
        offset = 0
        while True:
            chunk = _read_log_from(path, offset, token)
            if not chunk:
                break
            sys.stdout.buffer.write(chunk)
            offset += len(chunk)
            if not path.startswith(("http://", "https://")):
                break  # a local read() already returned the whole file
    except OSError as e:
        print(_log_read_diagnostic(pod, path, e), file=sys.stderr)
        return 1
    sys.stdout.flush()
    return 0


def cmd_nodes(client: TPUJobClient, args) -> int:
    """≙ `kubectl get nodes`: the execution plane at a glance — agent
    registrations, readiness, heartbeat age, capacity, and how many live
    pods each node is running."""
    from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE

    nodes = sorted(
        client.store.list("Node", NODE_NAMESPACE), key=lambda n: n.metadata.name
    )
    if not nodes:
        print("No nodes registered (single-node deployments run without "
              "agents; see executor/agent.py).")
        return 0
    pods = client.store.list("Pod")
    load = {}
    for p in pods:
        if p.spec.node_name and not p.is_finished():
            load[p.spec.node_name] = load.get(p.spec.node_name, 0) + 1
    now = time.time()
    rows = []
    for n in nodes:
        hb = n.status.last_heartbeat
        status = "Ready" if n.status.ready else "NotReady"
        if n.status.unschedulable:
            status += ",SchedulingDisabled"  # ≙ kubectl's cordon rendering
        rows.append([
            n.metadata.name,
            status,
            "static" if not hb else f"{max(0, now - hb):.1f}s",
            n.status.capacity_chips if n.status.capacity_chips is not None else "-",
            load.get(n.metadata.name, 0),
            n.status.address or "-",
        ])
    print(_table(rows, ["NAME", "STATUS", "HEARTBEAT", "CHIPS", "PODS", "ADDRESS"]))
    return 0


def _set_cordon(client: TPUJobClient, name: str, unschedulable: bool) -> bool:
    """Flip the cordon flag with ONE status-subresource merge-patch (oplint
    RMW001: this was the last GET+PUT+retry loop outside the patch seam —
    ten read-mutate-update attempts racing the agent's heartbeat, for a
    write that touches exactly one operator-owned key). A merge-patch of
    just ``status.unschedulable`` cannot clobber a concurrent heartbeat by
    construction (untouched keys are left alone), so no precondition and no
    retry loop are needed — the exact argument of the agent's own
    ``_heartbeat_status``."""
    from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE

    try:
        client.store.patch(
            "Node", NODE_NAMESPACE, name,
            {"status": {"unschedulable": unschedulable}}, subresource="status",
        )
        return True
    except NotFound:
        print(f"error: no node named {name!r} (see `ctl nodes`)",
              file=sys.stderr)
        return False


def cmd_cordon(client: TPUJobClient, args) -> int:
    """≙ kubectl cordon: mark the node unschedulable. Running pods stay;
    new gangs bind elsewhere. The flag survives agent heartbeats and is
    cleared only by uncordon."""
    if not _set_cordon(client, args.name, True):
        return 1
    print(f"node/{args.name} cordoned")
    return 0


def cmd_uncordon(client: TPUJobClient, args) -> int:
    """Clear the cordon flag AND any maintenance notice: the node returned
    from maintenance and is a binding target again (the DrainController
    level-triggers the Draining condition inactive once the notice is
    gone). Also clears the rescheduler's straggler flag — uncordon is the
    operator's 'this hardware is healthy again' verdict, and a stale flag
    would keep the scheduler deprioritizing a fixed node forever."""
    from mpi_operator_tpu.machinery.objects import (
        ANNOTATION_MAINTENANCE_AT,
        ANNOTATION_STRAGGLER_NODE,
        NODE_NAMESPACE,
    )

    if not _set_cordon(client, args.name, False):
        return 1
    try:
        client.store.patch(
            "Node", NODE_NAMESPACE, args.name,
            {"metadata": {"annotations": {ANNOTATION_MAINTENANCE_AT: None,
                                          ANNOTATION_STRAGGLER_NODE: None}}},
        )
    except NotFound:
        pass  # deleted between the two patches; nothing left to clear
    print(f"node/{args.name} uncordoned")
    return 0


def cmd_drain(client: TPUJobClient, args) -> int:
    """≙ kubectl drain, rebuilt on the disruption plane (ISSUE 14): stamp
    the ``tpujob.dev/maintenance-at`` notice (now + --deadline) and cordon;
    the leader's DrainController then evacuates the node end to end —
    batch gangs checkpoint-then-migrate (free restart), serve replicas
    migrate surge-first under their DisruptionBudget, and anything still
    bound at the deadline is hard-evicted. ``--status`` renders drain
    progress (exit 0 only when every draining node is empty). ``--now`` is
    the break-glass client-side path: evict immediately, no operator
    needed, no budget honored."""
    from mpi_operator_tpu.machinery.objects import (
        ANNOTATION_MAINTENANCE_AT,
        NODE_NAMESPACE,
        evict_pod,
    )

    if getattr(args, "status", False):
        return _drain_status(client, getattr(args, "name", None))
    if not getattr(args, "name", None):
        print("error: drain needs a node name (or --status)",
              file=sys.stderr)
        return 2
    if getattr(args, "now", False):
        if cmd_cordon(client, args) != 0:
            return 1
        evicted = []
        for pod in client.store.list("Pod"):
            if pod.spec.node_name != args.name or pod.is_finished():
                continue
            # break-glass immediate eviction is the sanctioned CLIENT-side
            # drain seam: no DrainController in the loop by design (the
            # operator may be down — that is what --now is for)
            if evict_pod(client.store, pod,  # oplint: disable=DIS001
                         f"node {args.name} drained (--now)"):
                evicted.append(
                    f"{pod.metadata.namespace}/{pod.metadata.name}"
                )
        for name in evicted:
            print(f"evicted pod {name}")
        print(f"node/{args.name} drained ({len(evicted)} pod(s) evicted)")
        return 0
    deadline_s = getattr(args, "deadline", None)
    if deadline_s is None:
        deadline_s = 3600.0
    if deadline_s <= 0:
        print("error: --deadline must be positive seconds", file=sys.stderr)
        return 2
    if cmd_cordon(client, args) != 0:
        return 1
    at = time.time() + deadline_s
    try:
        client.store.patch(
            "Node", NODE_NAMESPACE, args.name,
            {"metadata": {"annotations": {
                ANNOTATION_MAINTENANCE_AT: str(at),
            }}},
        )
    except NotFound:
        print(f"error: no node named {args.name!r}", file=sys.stderr)
        return 1
    print(f"node/{args.name} drain requested: maintenance deadline in "
          f"{deadline_s:.0f}s; the operator's drain controller is "
          f"evacuating (watch with `ctl drain --status`)")
    return 0


def _drain_status(client: TPUJobClient, only: Optional[str]) -> int:
    """The drain progress table (the ISSUE 14 runbook probe): one row per
    node with a maintenance notice — pods remaining, budget-blocked serve
    count, deadline countdown, Draining state. Exit 0 only when every
    shown node is EMPTY; exit 1 while anything is still evacuating or
    blocked (cron/CI can poll it like `ctl alerts`)."""
    from mpi_operator_tpu.controller.disruption import (
        DrainController,
        LABEL_SERVE_NAME,
    )
    from mpi_operator_tpu.machinery.objects import (
        NODE_NAMESPACE,
        maintenance_at,
        node_draining,
        node_has_maintenance,
    )

    nodes = [
        n for n in client.store.list("Node", NODE_NAMESPACE)
        if node_has_maintenance(n)
        and (only is None or n.metadata.name == only)
    ]
    if only is not None and not nodes:
        print(f"node/{only}: no maintenance notice (nothing draining)")
        return 0
    if not nodes:
        print("no nodes draining")
        return 0
    pods = client.store.list("Pod")
    now = time.time()
    rows = []
    busy = False
    for n in sorted(nodes, key=lambda n: n.metadata.name):
        live = [
            p for p in pods
            if p.spec.node_name == n.metadata.name and not p.is_finished()
        ]
        blocked = 0
        for ns, sname in sorted({
            (p.metadata.namespace, p.metadata.labels.get(LABEL_SERVE_NAME))
            for p in live if LABEL_SERVE_NAME in p.metadata.labels
        }):
            serve = client.store.try_get("TPUServe", ns, sname)
            if serve is not None and \
                    DrainController._serve_blocked_reason(serve):
                blocked += 1
        deadline = maintenance_at(n)
        left = "?" if deadline is None else f"{deadline - now:.0f}s"
        state = ("Draining" if node_draining(n)
                 else ("Drained" if not live else "Noticed"))
        if live:
            busy = True
        rows.append([
            n.metadata.name, state, len(live), blocked, left,
        ])
    print(_table(rows, ["NODE", "STATE", "PODS-REMAINING",
                        "BUDGET-BLOCKED", "DEADLINE-IN"]))
    return 1 if busy else 0


def _read_log_from(path: str, offset: int, token: Optional[str] = None) -> bytes:
    """Bytes from ``offset`` — local file seek, or the agent log endpoint's
    ``?offset=`` contract. Raises OSError on any read/fetch failure (THE one
    copy of the http-vs-local branching; cmd_logs and _follow_logs both ride
    it so the two paths can never diverge). ``token`` rides along as a
    bearer header for token-guarded agents (--token-file on the agent)."""
    if path.startswith("http://") or path.startswith("https://"):
        import urllib.error
        import urllib.request

        url = path if offset == 0 else (
            f"{path}{'&' if '?' in path else '?'}offset={offset}"
        )
        req = urllib.request.Request(
            url,
            headers={"Authorization": f"Bearer {token}"} if token else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.read()
        except urllib.error.URLError as e:
            raise OSError(str(e)) from None
    with open(path, "rb") as f:
        f.seek(offset)
        return f.read()


def _log_read_diagnostic(pod, path: str, err: Exception) -> str:
    where = pod.spec.node_name or "its node"
    if path.startswith("http://") or path.startswith("https://"):
        if "401" in str(err):
            return (f"error: {path} requires a token ({err}); pass "
                    f"--read-token-file — the admin token is never sent "
                    f"over plain HTTP (see log_token_for)")
        return (f"error: cannot fetch {path} ({err}); the pod ran on "
                f"{where} — is its node agent still up?")
    return (f"error: cannot read {path} here ({err}); the pod ran on "
            f"{where} — with agents, log paths are served as URLs")


def _follow_logs(client: TPUJobClient, pod, path: str,
                 token: Optional[str] = None) -> int:
    """≙ `kubectl logs -f`: stream the pod's output as it is written, exit
    when the pod finishes (0 on success; 130 on Ctrl-C like kubectl).
    Incremental byte-offset fetches — a log streamer's poll cadence, like
    the kubelet's follow mode. On observing a terminal phase the tail is
    fetched ONCE more (output flushed between our read and the phase check
    must not be dropped); persistent read failures surface as an error
    instead of an eternally silent stream."""
    import codecs

    decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
    offset = 0
    uid = pod.metadata.uid
    failures = 0

    def emit(chunk: bytes) -> None:
        nonlocal offset
        offset += len(chunk)
        sys.stdout.write(decoder.decode(chunk))
        sys.stdout.flush()

    try:
        while True:
            try:
                chunk = _read_log_from(path, offset, token)
                failures = 0
            except OSError as e:
                chunk = b""
                failures += 1
                if failures >= 10:  # ~5s of consecutive failures: not a blip
                    print(_log_read_diagnostic(pod, path, e), file=sys.stderr)
                    return 1
            if chunk:
                emit(chunk)
            cur = client.store.try_get(
                "Pod", pod.metadata.namespace, pod.metadata.name
            )
            if cur is None:
                return 0  # pod deleted: the stream is over
            if cur.metadata.uid != uid:
                print("\n(pod was restarted; re-run logs for the new "
                      "incarnation)", file=sys.stderr)
                return 1
            if cur.is_finished() and not chunk:
                try:
                    tail = _read_log_from(path, offset, token)
                except OSError:
                    tail = b""
                if tail:
                    emit(tail)
                return 0 if cur.status.phase == "Succeeded" else 1
            time.sleep(0.5)
    except KeyboardInterrupt:
        return 130


def cmd_store(client: TPUJobClient, args) -> int:
    """`ctl store status`: replica-set roles, lease time, applied rv and
    per-follower lag — the day-2 view of the HA store (≙ `etcdctl
    endpoint status`). Works against any store: non-replicated backends
    report one honest 'standalone' row. Against a wire-replicated set,
    ONE endpoint on the command line is enough — the survey follows each
    answer's peer hints to the full membership (discovered rows are
    marked '+'), and the leaderless-exit-1 contract holds in both output
    formats."""
    store = client.store
    status_fn = getattr(store, "replica_status", None)
    if callable(status_fn):
        rows_raw = status_fn()
    else:
        rows_raw = [{"endpoint": getattr(store, "path", type(store).__name__),
                     "role": "standalone"}]
    # exit 1 when the set has no live leader: scripts probe HA health
    # with this verb (the runbook's first triage command) — in EITHER
    # output format, or a monitor parsing json would miss leader loss
    rc = 0 if any(s.get("role") in ("leader", "standalone")
                  for s in rows_raw) else 1
    if args.output == "json":
        print(json.dumps(rows_raw, indent=2, sort_keys=True))
        return rc
    rows = []
    worst_lag = {}
    for s in rows_raw:
        if s.get("role") == "leader":
            worst_lag = s.get("lag_entries") or {}
        rows.append([
            (s.get("endpoint") or s.get("node", "-"))
            + ("+" if s.get("discovered") else ""),
            s.get("role", "?"),
            s.get("epoch", "-"),
            s.get("applied_rv", "-"),
            (f"{s['lease_remaining_s']}s"
             if "lease_remaining_s" in s else "-"),
            s.get("leader") or "-",
        ])
    print(_table(rows, ["ENDPOINT", "ROLE", "EPOCH", "APPLIED-RV",
                        "LEASE", "LEADER"]))
    if worst_lag:
        lagging = {k: v for k, v in worst_lag.items() if v}
        print("replication lag: "
              + (", ".join(f"{k}={v}" for k, v in sorted(lagging.items()))
                 if lagging else "0 entries (all followers caught up)"))
    return rc


def _serve_client(client: TPUJobClient):
    from mpi_operator_tpu.api.client import TPUServeClient

    return TPUServeClient(client.store, namespace=client.namespace)


def cmd_serve(client: TPUJobClient, args) -> int:
    """`ctl serve <action>`: the serving workload class's day-2 surface —
    create/get/status/scale/delete over TPUServe objects. `status` is the
    operator's view of a rollout/scale in flight: desired vs ready vs
    updated replicas, generation, autoscaler posture, and the per-gang
    table."""
    sc = _serve_client(client)
    action = args.action
    if action == "create" and not args.filename:
        print("error: serve create requires -f <manifest>", file=sys.stderr)
        return 2
    if action in ("status", "scale", "delete") and not args.name:
        print(f"error: serve {action} requires a name", file=sys.stderr)
        return 2
    if action == "scale" and args.replicas is None:
        print("error: serve scale requires --replicas", file=sys.stderr)
        return 2
    if action == "scale" and args.replicas < 0:
        print("error: --replicas must be >= 0", file=sys.stderr)
        return 2
    if action == "create":
        import yaml

        try:
            with open(args.filename) as f:
                doc = yaml.safe_load(f)
        except (OSError, yaml.YAMLError) as e:
            print(f"error: {args.filename}: {e}", file=sys.stderr)
            return 1
        try:
            serve = sc.create(doc)
        except (ManifestError, ValidationRejected, AlreadyExists) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(f"tpuserve.tpujob.dev/{serve.metadata.name} created")
        return 0
    if action == "get":
        if args.name:
            try:
                serves = [sc.get(args.name)]
            except NotFound as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
        else:
            serves = sc.list()
        if args.output == "json":
            docs = [s.to_dict() for s in serves]
            print(json.dumps(docs[0] if args.name else docs, indent=2))
            return 0
        if not serves:
            print("No tpuserves found.")
            return 0
        rows = [
            [
                s.metadata.name,
                f"{s.status.ready_replicas}/{s.spec.replicas or 0}",
                s.status.updated_replicas,
                s.status.serve_generation,
                "on" if s.spec.autoscale else "off",
                _age(s.metadata.creation_timestamp),
            ]
            for s in serves
        ]
        print(_table(rows, ["NAME", "READY", "UPDATED", "GEN",
                            "AUTOSCALE", "AGE"]))
        return 0
    if action == "delete":
        try:
            sc.delete(args.name)
        except NotFound as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(f"tpuserve.tpujob.dev/{args.name} deleted")
        return 0
    if action == "scale":
        try:
            serve = sc.get(args.name)
        except NotFound as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if serve.spec.autoscale is not None:
            print(
                f"warning: {args.name} is autoscaled; the autoscaler may "
                f"override this manual scale on its next decision",
                file=sys.stderr,
            )
        client.store.patch(
            "TPUServe", serve.namespace, serve.name,
            {"spec": {"replicas": args.replicas},
             "metadata": {"uid": serve.metadata.uid}},
        )
        print(f"tpuserve.tpujob.dev/{args.name} scaled to "
              f"{args.replicas} replicas")
        return 0
    # action == "status"
    try:
        serve = sc.get(args.name)
    except NotFound as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    from mpi_operator_tpu.controller.serve import (
        LABEL_SERVE_NAME,
        LABEL_SERVE_REPLICA,
    )

    m, sp, st = serve.metadata, serve.spec, serve.status
    lines = [
        f"Name:        {m.name}",
        f"Namespace:   {m.namespace}",
        f"Created:     {_age(m.creation_timestamp)} ago",
        f"Replicas:    {st.ready_replicas} ready / "
        f"{sp.replicas or 0} desired "
        f"({st.updated_replicas} at generation {st.serve_generation})",
        f"Gang size:   {sp.workers_per_replica or 1} worker(s) x "
        f"{sp.slice.chips_per_host or 1} chip(s)",
        f"Priority:    {sp.priority_class or 'high'}",
    ]
    asc = sp.autoscale
    if asc is not None:
        from mpi_operator_tpu.api.defaults import (
            DEFAULT_AUTOSCALE_MAX,
            DEFAULT_AUTOSCALE_MIN,
            DEFAULT_TARGET_QPS_PER_REPLICA,
        )

        lo = (asc.min_replicas if asc.min_replicas is not None
              else DEFAULT_AUTOSCALE_MIN)
        hi = (asc.max_replicas if asc.max_replicas is not None
              else DEFAULT_AUTOSCALE_MAX)
        tgt = (asc.target_qps_per_replica
               if asc.target_qps_per_replica is not None
               else DEFAULT_TARGET_QPS_PER_REPLICA)
        zero = (f", scale-to-zero after {asc.scale_to_zero_after_s:g}s"
                if asc.scale_to_zero_after_s is not None else "")
        lines.append(
            f"Autoscale:   {lo}..{hi} at {tgt:g} qps/replica{zero}"
        )
    lines.append("Conditions:")
    for c in st.conditions:
        lines.append(
            f"  {c.type:<13} {str(bool(c.status)):<6} {c.reason} — "
            f"{c.message}"
        )
    pods = client.store.list(
        "Pod", m.namespace, selector={LABEL_SERVE_NAME: m.name}
    )
    by_replica = {}
    for p in pods:
        rid = p.metadata.labels.get(LABEL_SERVE_REPLICA, "?")
        by_replica.setdefault(rid, []).append(p)
    if by_replica:
        rows = []
        for rid in sorted(by_replica, key=lambda r: int(r) if r.isdigit()
                          else -1):
            members = by_replica[rid]
            gen = members[0].metadata.labels.get("tpujob.dev/generation",
                                                 "?")
            ready = sum(1 for p in members if p.status.ready)
            qps = sum(
                float((p.status.serve_stats or {}).get("qps", 0.0))
                for p in members
            )
            nodes = ",".join(sorted({
                p.spec.node_name or "<unbound>" for p in members
            }))
            rows.append([f"r{rid}", gen, f"{ready}/{len(members)}",
                         f"{qps:g}", nodes])
        lines.append("Replicas:")
        lines.append("  " + _table(
            rows, ["GANG", "GEN", "READY", "QPS", "NODES"]
        ).replace("\n", "\n  "))
    print("\n".join(lines))
    return 0


def cmd_alerts(client: TPUJobClient, args) -> int:
    """`ctl alerts`: the SLO plane's firing state — every Alert object
    the burn-rate monitor has written, firing first. Exit 1 while
    anything is FIRING (the runbook's 'alert firing' row starts here:
    scripts and humans probe alert health with this one verb, like
    `ctl store status` probes HA health)."""
    from mpi_operator_tpu.api.types import ALERT_NAMESPACE

    alerts = client.store.list("Alert", ALERT_NAMESPACE)
    firing = [a for a in alerts if a.is_firing()]
    if args.output == "json":
        print(json.dumps([a.to_dict() for a in alerts], indent=2))
        return 1 if firing else 0
    if not alerts:
        print("No alerts recorded (the SLO monitor writes one per "
              "objective on its first firing).")
        return 0
    rows = []
    for a in sorted(alerts, key=lambda a: (not a.is_firing(),
                                           a.metadata.name)):
        st = a.status
        rows.append([
            a.metadata.name,
            a.spec.severity,
            st.state.upper() if a.is_firing() else st.state,
            _age(st.since if a.is_firing() else st.resolved_at),
            st.window or "-",
            f"{st.burn:g}x" if st.burn else "-",
            st.fired_count,
            st.message,
        ])
    print(_table(rows, ["OBJECTIVE", "SEV", "STATE", "AGE", "WINDOW",
                        "BURN", "FIRED", "MESSAGE"]))
    for a in firing:
        if a.status.incident:
            print(f"incident bundle: {a.status.incident}")
    return 1 if firing else 0


def _top_jobs(client: TPUJobClient) -> int:
    """`ctl top --jobs`: the workload-telemetry view — per-job GOODPUT /
    STEP-P50 / DOMINANT-STALL / STRAGGLER straight from the goodput
    aggregator's status.train_telemetry rollups. Exit 1 while any RUNNING
    job sits below the goodput-collapse floor (runbook probe parity with
    `ctl alerts`: scripts gate on the rc, humans read the table)."""
    floor = 0.0
    try:
        from mpi_operator_tpu.controller.slo_monitor import load_slo_config

        floor = load_slo_config().objective("goodput-collapse").bound
    except (ImportError, KeyError, ValueError):
        # custom SLO config without the objective (or none loadable from
        # this client): render the table, skip the rc gate
        floor = 0.0
    rows = []
    breached = []
    for j in sorted(client.store.list("TPUJob"),
                    key=lambda j: j.metadata.key()):
        state = job_state(j)
        tel = j.status.train_telemetry or {}
        goodput = tel.get("goodput")
        below = (
            state == "Running" and floor > 0
            and goodput is not None and goodput < floor
        )
        if below:
            breached.append(j.metadata.key())
        rows.append([
            j.metadata.key(),
            state,
            (f"{goodput:.0%}" + ("!" if below else ""))
            if goodput is not None else "-",
            f"{tel.get('step_p50_ms'):g}ms"
            if tel.get("step_p50_ms") else "-",
            tel.get("dominant_stall") or "-",
            tel.get("straggler") or "-",
            tel.get("steps", "-"),
        ])
    if not rows:
        print("no jobs")
        return 0
    print(_table(rows, ["JOB", "STATE", "GOODPUT", "STEP-P50",
                        "DOMINANT-STALL", "STRAGGLER", "STEPS"]))
    if breached:
        print(f"{len(breached)} running job(s) below the "
              f"goodput-collapse floor ({floor:g}): "
              f"{', '.join(breached)} — read the stall buckets "
              f"(`ctl describe`, runbook 'job slow')")
    return 1 if breached else 0


def _top_fragmentation(client: TPUJobClient) -> int:
    """`ctl top --fragmentation`: the defragmenting rescheduler's view —
    a contiguous-free-chips histogram across schedulable nodes, the
    largest gang member placeable right now, and every queued gang
    classified fits / blocked-fragmented / blocked-capacity. Exit 1
    while any queued gang fits total-free but not contiguous-free
    (pure fragmentation: the rescheduler's make-room trigger — the
    'fleet fragmented' runbook row starts here)."""
    from collections import Counter

    from mpi_operator_tpu.machinery.objects import (
        ANNOTATION_MAINTENANCE_AT,
        ANNOTATION_STRAGGLER_NODE,
        NODE_NAMESPACE,
    )
    from mpi_operator_tpu.controller.disruption import LABEL_SERVE_NAME
    from mpi_operator_tpu.scheduler.gang import (
        LABEL_JOB_NAME,
        GangScheduler,
        pod_cost,
    )

    nodes = client.store.list("Node", NODE_NAMESPACE)
    pods = client.store.list("Pod")
    live = [n for n in nodes
            if n.status.ready and not n.status.unschedulable]
    used = GangScheduler._node_used(pods)
    schedulable = [
        n for n in live
        if ANNOTATION_MAINTENANCE_AT not in n.metadata.annotations
    ]
    free = {
        n.metadata.name:
            max(0, (n.status.capacity_chips or 0)
                - used.get(n.metadata.name, 0))
        for n in schedulable
    }
    largest = max(free.values(), default=0)
    total = sum(free.values())
    flagged = sum(
        1 for n in schedulable
        if ANNOTATION_STRAGGLER_NODE in n.metadata.annotations
    )
    lines = [
        f"FREE CHIPS  total={total}  largest-contiguous={largest}  "
        f"nodes={len(schedulable)} schedulable"
        + (f"  straggler-flagged={flagged}" if flagged else ""),
    ]
    hist = Counter(free.values())
    for chips in sorted(hist, reverse=True):
        n = hist[chips]
        lines.append(f"  free={chips:<4d} {'#' * n} {n} node(s)")
    # queued gangs: pending unbound batch pods grouped by job label
    pending: dict = {}
    for p in pods:
        if p.spec.node_name or p.is_finished():
            continue
        gang = p.metadata.labels.get(LABEL_JOB_NAME)
        if gang and LABEL_SERVE_NAME not in p.metadata.labels:
            pending.setdefault((p.metadata.namespace, gang), []).append(p)
    fragmented = []
    if pending:
        lines.append("QUEUED GANGS")
    for (ns, gang), members in sorted(pending.items()):
        members.sort(key=lambda p: p.metadata.name)
        costs = [pod_cost(p) for p in members]
        scratch = dict(used)
        placeable = True
        for c in costs:
            target = GangScheduler._pick_node(live, scratch, c)
            if target is None:
                placeable = False
                break
            scratch[target] = scratch.get(target, 0) + c
        if placeable:
            verdict = "fits"
        elif sum(costs) <= total:
            verdict = "BLOCKED-FRAGMENTED"
            fragmented.append(f"{ns}/{gang}")
        else:
            verdict = "blocked-capacity"
        lines.append(f"  {ns}/{gang:<24s} pods={len(members)} "
                     f"chips={sum(costs)}  {verdict}")
    if fragmented:
        lines.append(
            f"FRAGMENTED  {len(fragmented)} gang(s) fit total-free but "
            f"not contiguous-free: {', '.join(fragmented)} — the "
            f"rescheduler should be defragmenting (see "
            f"tpu_operator_rescheduler_parked if it is not)"
        )
    print("\n".join(lines))
    return 1 if fragmented else 0


def cmd_top(client: TPUJobClient, args) -> int:
    """`ctl top`: the one-scrape cluster overview — jobs by phase, chips
    held vs capacity, node/pod health, firing alerts from the store; and
    with --metrics URL(s), store p99 by verb, reconcile/watch-lag
    percentiles, and tenant shed counts read straight out of live
    /metrics expositions (since-process-start quantiles: the trend view
    is the monitor's windowed job, this is the snapshot). `--jobs`
    switches to the per-job workload-telemetry table (goodput / stall
    attribution / stragglers)."""
    if getattr(args, "jobs", False):
        return _top_jobs(client)
    if getattr(args, "fragmentation", False):
        return _top_fragmentation(client)
    import urllib.request

    import math

    from mpi_operator_tpu.api.types import ALERT_NAMESPACE
    from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE
    from mpi_operator_tpu.opshell.metrics import (
        histogram_quantile,
        parse_exposition,
    )
    from mpi_operator_tpu.scheduler.gang import pod_cost

    def _quantile(fams, family, q, **labels):
        """histogram_quantile straight off the ALREADY-PARSED families
        (exposition_quantile would re-parse the whole text per call —
        O(combos × text) across the verb table)."""
        pairs = []
        for name, lbls, value in fams[family]["samples"]:
            if not name.endswith("_bucket"):
                continue
            rest = {k: v for k, v in lbls.items() if k != "le"}
            if rest != labels:
                continue
            le = lbls.get("le", "")
            pairs.append((math.inf if le == "+Inf" else float(le),
                          int(value)))
        pairs.sort()
        return histogram_quantile(q, pairs)

    lines = []
    jobs = client.store.list("TPUJob")
    by_state: dict = {}
    for j in jobs:
        by_state[job_state(j)] = by_state.get(job_state(j), 0) + 1
    lines.append(f"JOBS        {len(jobs)} total"
                 + ("".join(f"  {k}={v}" for k, v in sorted(by_state.items()))
                    if by_state else ""))
    serves = client.store.list("TPUServe")
    if serves:
        ready = sum(s.status.ready_replicas for s in serves)
        desired = sum(s.spec.replicas or 0 for s in serves)
        lines.append(f"SERVES      {len(serves)} total  ready={ready}/"
                     f"{desired}")
    nodes = client.store.list("Node", NODE_NAMESPACE)
    if nodes:
        ready_n = sum(1 for n in nodes if n.status.ready)
        cordoned = sum(1 for n in nodes if n.status.unschedulable)
        capacity = sum(n.status.capacity_chips or 0 for n in nodes)
        lines.append(f"NODES       {len(nodes)} total  ready={ready_n}"
                     + (f"  cordoned={cordoned}" if cordoned else ""))
    else:
        capacity = 0
    pods = client.store.list("Pod")
    by_phase: dict = {}
    held = 0
    for p in pods:
        by_phase[p.status.phase] = by_phase.get(p.status.phase, 0) + 1
        if p.spec.node_name and not p.is_finished():
            held += pod_cost(p)
    lines.append(f"PODS        {len(pods)} total"
                 + "".join(f"  {k}={v}" for k, v in sorted(by_phase.items())))
    lines.append(f"CHIPS       held={held}"
                 + (f" / capacity={capacity}" if capacity else ""))
    alerts = client.store.list("Alert", ALERT_NAMESPACE)
    firing = sorted(a.metadata.name for a in alerts if a.is_firing())
    lines.append("ALERTS      "
                 + (f"{len(firing)} FIRING: {', '.join(firing)} "
                    f"(see `ctl alerts`)" if firing else
                    f"none firing ({len(alerts)} recorded)"))
    print("\n".join(lines))

    for spec in (args.metrics or "").split(","):
        spec = spec.strip()
        if not spec:
            continue
        name, sep, url = spec.partition("=")
        if not sep:
            name, url = "", spec
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                text = r.read().decode("utf-8", "replace")
            fams = parse_exposition(text)
        except Exception as e:
            print(f"\n{name or url}: scrape failed: {e}", file=sys.stderr)
            continue
        print(f"\n== {name or url} ==")
        fam = "tpu_operator_store_request_latency_seconds"
        if fam in fams:
            combos = sorted({
                (lbl.get("verb", ""), lbl.get("backend", ""))
                for n, lbl, _ in fams[fam]["samples"]
                if n.endswith("_count")
            })
            rows = []
            for verb, backend in combos:
                count = sum(
                    v for n, lbl, v in fams[fam]["samples"]
                    if n.endswith("_count") and lbl.get("verb") == verb
                    and lbl.get("backend") == backend
                )
                rows.append([
                    verb, backend, int(count),
                    f"{_quantile(fams, fam, 0.5, verb=verb, backend=backend) * 1e3:.1f}",
                    f"{_quantile(fams, fam, 0.99, verb=verb, backend=backend) * 1e3:.1f}",
                ])
            if rows:
                print(_table(rows, ["VERB", "BACKEND", "COUNT",
                                    "P50MS", "P99MS"]))
        for label, family in (
            ("reconcile", "tpu_operator_reconcile_latency_seconds"),
            ("watch-lag", "tpu_operator_watch_delivery_lag_seconds"),
            ("bind", "tpu_operator_scheduler_bind_latency_seconds"),
        ):
            if family in fams and any(
                n.endswith("_count") and v > 0
                for n, _, v in fams[family]["samples"]
            ):
                p50 = _quantile(fams, family, 0.5) * 1e3
                p99 = _quantile(fams, family, 0.99) * 1e3
                print(f"{label}: p50 {p50:.1f}ms  p99 {p99:.1f}ms")
        shed = [
            (lbl.get("tenant", "?"), lbl.get("reason", ""), v)
            for n, lbl, v in fams.get(
                "tpu_operator_store_tenant_rejected_total",
                {"samples": []})["samples"]
            if v > 0
        ]
        if shed:
            print("tenant shed (429s): " + ", ".join(
                f"{t}={v:g}" + (f" ({r})" if r else "")
                for t, r, v in sorted(shed)))
    return 0


def cmd_profile(client: TPUJobClient, args) -> int:
    """`ctl profile <job> --steps N`: attach the profiler to a live gang
    — stamps the tpujob.dev/profile-request annotation; the controller
    projects it into the job's config dir, every worker captures a
    jax.profiler trace for N steps into the job's artifact dir and acks
    through its train_stats. `--status` renders the acks, `--fetch`
    collects the trace dirs (local/shared filesystem — the single-host
    and shared-volume shapes; cross-node collection rides the same
    artifact volume checkpoints already require)."""
    import shutil
    import uuid

    from mpi_operator_tpu.machinery.objects import ANNOTATION_PROFILE_REQUEST

    try:
        job = client.get(args.name)
    except NotFound as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    current = {}
    raw = job.metadata.annotations.get(ANNOTATION_PROFILE_REQUEST, "")
    if raw:
        try:
            current = json.loads(raw)
        except ValueError:
            current = {}

    def profile_acks():
        pods = client.store.list(
            "Pod", job.metadata.namespace,
            selector={"tpujob.dev/job-name": job.metadata.name},
        )
        out = []
        for p in sorted(pods, key=lambda p: p.metadata.name):
            if p.is_finished():
                continue
            prof = (p.status.train_stats or {}).get("profile") or {}
            out.append((p.metadata.name, prof))
        return out

    if args.status or args.fetch:
        want = str(current.get("id", ""))
        if not want:
            print(f"error: job {args.name} has no profile request "
                  f"(run `ctl profile {args.name} --steps N` first)",
                  file=sys.stderr)
            return 1
        acks = profile_acks()
        matching = [(n, p) for n, p in acks if p.get("id") == want]
        if args.status:
            rows = [
                [n, p.get("id") or "-", p.get("state") or "pending",
                 p.get("dir") or "-"]
                for n, p in acks
            ]
            print(_table(rows, ["POD", "REQUEST", "STATE", "DIR"])
                  if rows else "no live worker pods")
            # done means EVERY live worker acked THIS request done — a
            # subset-done rc=0 would let a script --fetch half the
            # gang's traces with no error
            done = bool(acks) and all(
                p.get("id") == want and p.get("state") == "done"
                for _, p in acks
            )
            return 0 if done else 1
        # --fetch: collect every completed capture's trace dir
        dest = args.dest or f"profile-{args.name}-{want}"
        fetched = 0
        for n, p in matching:
            if p.get("state") != "done":
                continue
            src = p.get("dir") or ""
            if not src or not os.path.isdir(src):
                print(f"warning: {n}: trace dir {src or '<none>'} not "
                      f"readable from here (fetch from the artifact "
                      f"volume)", file=sys.stderr)
                continue
            target = os.path.join(dest, n)
            shutil.copytree(src, target, dirs_exist_ok=True)
            fetched += 1
            print(f"{n}: fetched {src} -> {target}")
        if not fetched:
            print("error: no completed captures to fetch (try --status)",
                  file=sys.stderr)
            return 1
        return 0

    # stamp a fresh request (one in flight per job; the id disambiguates)
    req_id = uuid.uuid4().hex[:8]
    req = json.dumps({"id": req_id, "steps": int(args.steps),
                      "at": round(time.time(), 3)})
    try:
        client.store.patch(
            "TPUJob", job.metadata.namespace, job.metadata.name,
            # uid-pinned: a recreated same-name job must not absorb a
            # stale profile request aimed at its predecessor
            {"metadata": {"uid": job.metadata.uid,
                          "annotations": {ANNOTATION_PROFILE_REQUEST: req}}},
        )
    except (Conflict, NotFound) as e:
        # deleted or recreated between the read and this stamp
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"profile request {req_id} stamped: {args.steps} steps; "
          f"workers pick it up at their next membership check — poll "
          f"with `ctl profile {args.name} --status`, collect with "
          f"--fetch")
    return 0


def cmd_trace(client: TPUJobClient, args) -> int:
    """`ctl trace <job>` / `ctl trace --last-incident`: the causal
    timeline of a job's lifecycle (submit → scheduled → launched →
    running → restarts/failovers → terminal), rendered from the spans
    every component exported under the trace dir (machinery/trace.py).
    The runbook's first stop for "why did job X restart, and where did
    the time go?"."""
    from mpi_operator_tpu.machinery import trace as tr

    trace_dir = args.trace_dir or os.environ.get(tr.ENV_TRACE_DIR)
    if not trace_dir:
        print("error: no trace dir — pass --trace-dir or set "
              f"{tr.ENV_TRACE_DIR} (the operator/agents/store must have "
              "run with it to have exported spans)", file=sys.stderr)
        return 2
    spans = tr.load_spans(trace_dir)
    if not spans:
        print(f"error: no spans found under {trace_dir}", file=sys.stderr)
        return 1
    if args.last_incident:
        incident = tr.last_incident(spans)
        if incident is None:
            print("no incident spans (gang restart / failover / node "
                  "loss / SLO alert) recorded")
            return 0
        print(tr.render_incident(spans, incident))
        # the flight-recorder link: an slo.alert incident carries its
        # bundle path as a span attribute; otherwise link the newest
        # bundle in the incident dir (same triage either way)
        from mpi_operator_tpu.controller.slo_monitor import FlightRecorder

        bundle = (incident.get("attrs") or {}).get("bundle")
        if not bundle:
            inc_dir = os.environ.get("TPUJOB_INCIDENT_DIR") or os.path.join(
                trace_dir, "incidents")
            bundle = FlightRecorder.newest_bundle(inc_dir)
        if bundle and os.path.exists(bundle):
            try:
                with open(bundle, encoding="utf-8") as f:
                    b = json.load(f)
                print(f"\nincident bundle: {bundle}")
                print(f"  objective: {b.get('objective', '?')}  "
                      f"spans: {len(b.get('spans', []))}  "
                      f"events: {len(b.get('events', []))}  "
                      f"watch tail: {len(b.get('watch_events', []))}")
                burns = b.get("burns") or {}
                if burns:
                    print("  burns: " + "  ".join(
                        f"{k}={v:.1f}x" for k, v in sorted(burns.items())))
            except (OSError, ValueError) as e:
                print(f"\nincident bundle: {bundle} (unreadable: {e})",
                      file=sys.stderr)
        return 0
    if not args.name:
        print("error: a job name (or --last-incident) is required",
              file=sys.stderr)
        return 2
    try:
        job = client.get(args.name)
        tid = job.metadata.annotations.get(tr.ANNOTATION_TRACE_ID)
        header = [f"TPUJob {job.metadata.namespace}/{job.metadata.name}"]
        for c in job.status.conditions:
            header.append(
                f"  {c.type:<12} {str(bool(c.status)):<6} {c.reason}"
            )
        if job.status.restart_count or job.status.restart_generation:
            header.append(
                f"  restarts: count={job.status.restart_count} "
                f"generation={job.status.restart_generation}"
            )
    except NotFound:
        serve = None
        try:
            serve = _serve_client(client).get(args.name)
        except NotFound:
            pass
        if serve is not None:
            # the serving workload class: `ctl trace <serve>` renders the
            # rollout timeline (serve.rollout → replica_launch →
            # replica_ready → replica_drain) the serve controller exported
            tid = serve.metadata.annotations.get(tr.ANNOTATION_TRACE_ID)
            st = serve.status
            header = [f"TPUServe {serve.metadata.namespace}/"
                      f"{serve.metadata.name}"]
            for c in st.conditions:
                header.append(
                    f"  {c.type:<13} {str(bool(c.status)):<6} {c.reason}"
                )
            header.append(
                f"  replicas: {st.ready_replicas} ready / "
                f"{st.replicas} live, generation {st.serve_generation}"
            )
        else:
            # deleted jobs/serves still have their spans; fall back to the
            # newest trace that names the object in a span attribute. Pod
            # attrs match on the worker-name shape
            # ("<ns>/<job>-worker-N"), never a bare prefix — job "train"
            # must not adopt job "train2"'s trace.
            tid = None
            header = [f"{client.namespace}/{args.name} (deleted; "
                      f"reconstructing from spans)"]
            needle = f"{client.namespace}/{args.name}"
            pod_prefix = f"{needle}-worker-"
            for s in spans:
                attrs = s.get("attrs") or {}
                if (
                    attrs.get("job") == needle
                    or attrs.get("serve") == needle
                    or str(attrs.get("pod", "")).startswith(pod_prefix)
                ):
                    tid = s.get("trace_id")
    if not tid:
        print(f"error: job {args.name} carries no trace id (created "
              "before tracing, or by an old client) and no span "
              "mentions it", file=sys.stderr)
        return 1
    print("\n".join(header))
    print(tr.render_timeline(spans, tid, title=f"trace {tid}"))
    return 0


def cmd_watch(client: TPUJobClient, args) -> int:
    """Stream state transitions until the job finishes (≙ kubectl get -w —
    which rides the watch API, so this does too: the store's watch queue
    delivers changes instead of a get round-trip every 200ms)."""
    import queue

    from mpi_operator_tpu.machinery.store import DELETED

    q = client.store.watch("TPUJob")  # register BEFORE the initial read
    try:
        try:
            job = client.get(args.name)
        except NotFound as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        last = None
        deadline = time.time() + args.timeout

        def emit(job) -> Optional[int]:
            nonlocal last
            state = job_state(job)
            if state != last:
                print(f"{job.metadata.name}  {state}", flush=True)
                last = state
            if is_finished(job.status):
                return 0 if is_succeeded(job.status) else 1
            return None

        rc = emit(job)
        if rc is not None:
            return rc
        while time.time() < deadline:
            try:
                ev = q.get(timeout=max(0.01, min(deadline - time.time(), 1.0)))
            except queue.Empty:
                # idle resync: a deletion inside a watch/relist gap emits no
                # DELETED event (relists re-deliver live objects only), so
                # level-check once per idle second
                try:
                    job = client.get(args.name)
                except NotFound:
                    print(f"{args.name}  <deleted>")
                    return 0
                rc = emit(job)
                if rc is not None:
                    return rc
                continue
            m = ev.obj.metadata
            if m.name != args.name or m.namespace != client.namespace:
                continue
            if ev.type == DELETED:
                print(f"{args.name}  <deleted>")
                return 0
            rc = emit(ev.obj)
            if rc is not None:
                return rc
        print(f"error: timed out after {args.timeout}s", file=sys.stderr)
        return 1
    finally:
        client.store.stop_watch(q)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="tpujobctl", description=__doc__)
    # required, no 'memory' default: a client CLI on a private in-process
    # store would print success and affect nothing
    ap.add_argument("--store", required=True,
                    help="'sqlite:PATH' or 'http://HOST:PORT' (the shared "
                         "store an operator is running on)")
    ap.add_argument("--token-file", default=None,
                    help="bearer token file for an authenticated http store")
    ap.add_argument("--read-token-file", default=None,
                    help="READ-ONLY token file: when given, `ctl logs` "
                         "presents THIS to agent log endpoints instead of "
                         "the admin token — log fetches cross per-node "
                         "servers (plain HTTP), so send the least-"
                         "privileged credential that works there")
    ap.add_argument("--tls-ca-file", default=None,
                    help="CA bundle (or the self-signed cert itself) to "
                         "verify a --store https://... against")
    ap.add_argument("-n", "--namespace", default="default")
    sub = ap.add_subparsers(dest="verb", required=True)
    p = sub.add_parser("create", help="submit a TPUJob manifest")
    p.add_argument("-f", "--filename", required=True)
    p = sub.add_parser("get", help="list jobs, or one job")
    p.add_argument("name", nargs="?")
    p.add_argument("-o", "--output", choices=["table", "json"], default="table")
    p = sub.add_parser("describe", help="job detail: spec, conditions, pods, events")
    p.add_argument("name")
    p = sub.add_parser("delete", help="delete a job")
    p.add_argument("name")
    p = sub.add_parser("events", help="the job's event audit trail")
    p.add_argument("name")
    p = sub.add_parser("scale", help="change worker replicas on a live job "
                                     "(the elastic entry point)")
    p.add_argument("name")
    p.add_argument("--replicas", type=int, required=True)
    p = sub.add_parser("suspend", help="set runPolicy.suspend: the gang is "
                                       "drained, the job holds")
    p.add_argument("name")
    p = sub.add_parser("resume", help="clear runPolicy.suspend")
    p.add_argument("name")
    p = sub.add_parser("logs", help="print a pod's stdout (pod name, or job "
                                    "name for its coordinator pod)")
    p.add_argument("name")
    p.add_argument("--stderr", action="store_true",
                   help="print the stderr stream instead")
    p.add_argument("-f", "--follow", action="store_true",
                   help="stream the log as it is written, until the pod "
                        "finishes (like kubectl logs -f)")
    p = sub.add_parser("watch", help="stream state transitions until finished")
    p.add_argument("name")
    p.add_argument("--timeout", type=float, default=600.0)
    sub.add_parser("nodes", help="list registered execution nodes "
                                 "(the agent fleet; like kubectl get nodes)")
    p = sub.add_parser("cordon", help="mark a node unschedulable "
                                      "(running pods stay)")
    p.add_argument("name")
    p = sub.add_parser("uncordon", help="clear a node's cordon flag")
    p.add_argument("name")
    p = sub.add_parser("drain", help="stamp a maintenance notice on a node "
                                     "(the operator's drain controller "
                                     "then migrates its gangs off, budget-"
                                     "aware); --status shows progress, "
                                     "--now evicts immediately client-side")
    p.add_argument("name", nargs="?",
                   help="node name (optional with --status: all draining)")
    p.add_argument("--deadline", type=float, default=None, metavar="S",
                   help="seconds until the maintenance window fires "
                        "(default 3600); pods still bound then are "
                        "hard-evicted")
    p.add_argument("--status", action="store_true",
                   help="render drain progress; exit 0 only when every "
                        "draining node is empty, 1 while evacuating")
    p.add_argument("--now", action="store_true",
                   help="break-glass: cordon + evict immediately from this "
                        "client (no operator, no budget)")
    p = sub.add_parser("store", help="store backend introspection "
                                     "(replica roles, lease, lag)")
    p.add_argument("action", choices=["status"])
    p.add_argument("-o", "--output", choices=["table", "json"],
                   default="table")
    p = sub.add_parser("serve", help="the serving workload class "
                                     "(TPUServe): create/get/status/"
                                     "scale/delete autoscaled inference "
                                     "gangs")
    p.add_argument("action",
                   choices=["create", "get", "status", "scale", "delete"])
    p.add_argument("name", nargs="?",
                   help="serve name (required for status/scale/delete)")
    p.add_argument("-f", "--filename", default=None,
                   help="TPUServe manifest (create)")
    p.add_argument("--replicas", type=int, default=None,
                   help="target replica count (scale)")
    p.add_argument("-o", "--output", choices=["table", "json"],
                   default="table")
    p = sub.add_parser("alerts", help="the SLO plane's firing state "
                                      "(Alert objects the burn-rate "
                                      "monitor wrote); exit 1 while "
                                      "anything is FIRING")
    p.add_argument("-o", "--output", choices=["table", "json"],
                   default="table")
    p = sub.add_parser("top", help="one-scrape cluster overview: jobs by "
                                   "phase, chips held, nodes, alerts; "
                                   "--metrics adds store p99 by verb, "
                                   "reconcile/watch-lag percentiles and "
                                   "tenant shed counts from live /metrics")
    p.add_argument("--metrics", default=None, metavar="MAP",
                   help="comma list of [name=]http://host:port/metrics "
                        "endpoints to scrape once (operator "
                        "--monitoring-port, tpu-store --monitoring-port)")
    p.add_argument("--jobs", action="store_true",
                   help="per-job workload telemetry: GOODPUT / STEP-P50 / "
                        "DOMINANT-STALL / STRAGGLER from the goodput "
                        "aggregator's rollups; exit 1 while any running "
                        "job is below the goodput-collapse floor")
    p.add_argument("--fragmentation", action="store_true",
                   help="contiguous-free-chips histogram + largest "
                        "schedulable gang member + queued-gang verdicts; "
                        "exit 1 while a queued gang fits total-free but "
                        "not contiguous-free (fleet fragmented)")
    p = sub.add_parser("profile", help="attach the profiler to a live "
                                       "gang: stamp a profile request "
                                       "(workers capture N steps of "
                                       "jax.profiler trace); --status "
                                       "shows acks, --fetch collects")
    p.add_argument("name", help="job name")
    p.add_argument("--steps", type=int, default=5,
                   help="steps to capture per worker (default 5)")
    p.add_argument("--status", action="store_true",
                   help="render per-pod capture acks; exit 0 once every "
                        "reporting worker finished the current request")
    p.add_argument("--fetch", action="store_true",
                   help="copy completed trace dirs here (or --dest)")
    p.add_argument("--dest", default=None,
                   help="fetch destination (default "
                        "./profile-<job>-<request-id>)")
    p = sub.add_parser("trace", help="render a job's causal span timeline "
                                     "(submit → scheduled → launched → "
                                     "restarts → terminal) from the "
                                     "exported trace dir")
    p.add_argument("name", nargs="?",
                   help="job name (omit with --last-incident)")
    p.add_argument("--trace-dir", default=None,
                   help=f"span export dir (default: ${{{'TPUJOB_TRACE_DIR'}}})")
    p.add_argument("--last-incident", action="store_true",
                   help="reconstruct the most recent gang restart / "
                        "failover / node loss instead of a named job")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.store == "memory":
        # build_store would hand back a private in-process store: every verb
        # would "succeed" against state nobody else can see
        print("error: --store memory is not usable from a client CLI; "
              "point at a shared store (sqlite:PATH or http://HOST:PORT)",
              file=sys.stderr)
        return 2
    from mpi_operator_tpu.machinery import trace as _tr
    from mpi_operator_tpu.machinery.http_store import read_token_file
    from mpi_operator_tpu.opshell.__main__ import build_store

    # `ctl create` under TPUJOB_TRACE_DIR exports the client.submit span —
    # the "submit" entry `ctl trace` renders at the head of the timeline
    _tr.configure_from_env("ctl")
    try:
        token = read_token_file(args.token_file)
        read_token = read_token_file(args.read_token_file)
    except (OSError, ValueError) as e:
        print(f"error: token file: {e}", file=sys.stderr)
        return 2
    # `ctl logs` crosses per-node log servers: the credential sent there is
    # chosen PER URL by log_token_for — read token preferred, admin token
    # over TLS only, nothing on a plaintext seam (the admin bearer on plain
    # HTTP was the VERDICT's credential leak). The STORE client conversely
    # uses the strongest credential in hand — a viewer running with only
    # --read-token-file still authenticates its reads.
    args.log_admin_token = token
    args.log_read_token = read_token
    store = build_store(args.store, token=token or read_token,
                        ca_file=args.tls_ca_file)
    client = TPUJobClient(store, namespace=args.namespace)
    try:
        return {
            "create": cmd_create,
            "get": cmd_get,
            "describe": cmd_describe,
            "delete": cmd_delete,
            "events": cmd_events,
            "logs": cmd_logs,
            "scale": cmd_scale,
            "suspend": cmd_suspend,
            "resume": cmd_resume,
            "watch": cmd_watch,
            "nodes": cmd_nodes,
            "cordon": cmd_cordon,
            "uncordon": cmd_uncordon,
            "drain": cmd_drain,
            "store": cmd_store,
            "serve": cmd_serve,
            "trace": cmd_trace,
            "alerts": cmd_alerts,
            "top": cmd_top,
            "profile": cmd_profile,
        }[args.verb](client, args)
    except Forbidden as e:
        # read-tier token on a mutating verb: authenticated but not
        # authorized — say so plainly (≙ kubectl's 'forbidden' errors)
        print(f"error: {e}", file=sys.stderr)
        return 2
    except Unauthorized as e:
        # a wrong/missing token must read as a CLI error with the server's
        # hint, not a PermissionError traceback
        print(f"error: {e} (pass --token-file for an authenticated store)",
              file=sys.stderr)
        return 2
    finally:
        close = getattr(store, "close", None)
        if close is not None:
            close()


if __name__ == "__main__":
    sys.exit(main())
