"""Operational shell: metrics, health, leader election, CLI entry points.

≙ /root/reference/v2/cmd/mpi-operator/ (flags, leader election, /healthz,
Prometheus wiring, SURVEY.md §2.3/§5.5).
"""
