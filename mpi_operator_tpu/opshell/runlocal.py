"""runlocal: run a TPUJob manifest end-to-end on this machine.

The minimum end-to-end slice of SURVEY.md §7 phase 5, as a CLI:

  python -m mpi_operator_tpu.opshell.runlocal examples/pi.yaml

manifest → defaults → validation → controller reconcile (service, config,
gang placement, worker pods) → LocalExecutor runs each worker as an OS
process (SPMD boot via the injected TPUJOB_* env) → pod phases mirror into
job conditions → exit 0 iff the job reaches Succeeded.

≙ the reference's documented smoke-test flow `kubectl create -f
examples/pi/pi.yaml && kubectl logs pi-launcher` (examples/pi/README.md) —
with the cluster replaced by the in-process store + executor.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import yaml

from mpi_operator_tpu.api.conditions import is_finished, is_succeeded
from mpi_operator_tpu.api.schema import parse_tpujob
from mpi_operator_tpu.api.types import TPUJob
from mpi_operator_tpu.controller.controller import ControllerOptions, TPUJobController
from mpi_operator_tpu.executor import LocalExecutor
from mpi_operator_tpu.machinery.events import EventRecorder
from mpi_operator_tpu.machinery.store import ObjectStore
from mpi_operator_tpu.scheduler import GangScheduler, SliceInventory


def load_job(path: str) -> TPUJob:
    """Load a manifest through the strict structural schema: unknown or
    typo'd fields fail loudly (≙ apiserver CRD schema rejection)."""
    with open(path) as f:
        doc = yaml.safe_load(f)
    return parse_tpujob(doc)


def run_job(
    job: TPUJob,
    *,
    timeout: float = 300.0,
    workdir: str | None = None,
    chips: int | None = None,
    inventory: "str | SliceInventory | None" = None,
) -> tuple:
    """Drive one job to completion; returns (final job, worker logs dict).

    ``chips`` bounds the gang scheduler's inventory (None = unbounded);
    ``inventory`` switches to topology-aware admission (a SliceInventory,
    or a spec string like ``"4x4,4x4"``). Either way admission is enforced:
    pods launch only once the whole gang is bound (scheduler/gang.py)."""
    if isinstance(inventory, str):
        inventory = SliceInventory.parse(inventory)
    store = ObjectStore()
    recorder = EventRecorder(store)
    controller = TPUJobController(store, recorder, ControllerOptions())
    scheduler = GangScheduler(store, recorder, chips=chips, inventory=inventory)
    executor = LocalExecutor(store, workdir=workdir, require_binding=True)
    store.create(job)
    controller.run()
    scheduler.start()
    executor.start()
    deadline = time.time() + timeout
    final = None
    try:
        while time.time() < deadline:
            cur = store.get("TPUJob", job.metadata.namespace, job.metadata.name)
            if is_finished(cur.status):
                final = cur
                break
            time.sleep(0.1)
        else:
            raise TimeoutError(
                f"job {job.metadata.name} did not finish within {timeout}s"
            )
    finally:
        executor.stop()
        scheduler.stop()
        controller.stop()
    return final, dict(executor.logs)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="runlocal", description=__doc__)
    ap.add_argument("manifest", help="TPUJob YAML/JSON manifest")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--chips", type=int, default=None,
                    help="bound the scheduler's chip inventory")
    ap.add_argument("--inventory", default=None,
                    help="topology-aware inventory (host meshes per physical "
                         "slice, e.g. '4x4,4x4')")
    ap.add_argument("--events", action="store_true", help="print the event log")
    args = ap.parse_args(argv)
    from mpi_operator_tpu.machinery import trace

    trace.configure_from_env("runlocal")
    inventory = None
    if args.inventory is not None:
        try:
            inventory = SliceInventory.parse(args.inventory)
        except ValueError as e:
            print(f"error: --inventory: {e}", file=sys.stderr)
            return 2
    job = load_job(args.manifest)
    store_job, logs = run_job(
        job, timeout=args.timeout, workdir=args.workdir, chips=args.chips,
        inventory=inventory,
    )

    # worker 0 plays the launcher; its output is the job's output
    # (≙ `kubectl logs <job>-launcher`, examples/pi/README.md)
    coord_key = f"{store_job.metadata.namespace}/{store_job.metadata.name}-worker-0"
    if coord_key in logs and logs[coord_key][0].strip():
        print(logs[coord_key][0].strip())

    status = {
        "job": f"{store_job.metadata.namespace}/{store_job.metadata.name}",
        "conditions": [
            {"type": c.type, "status": c.status, "reason": c.reason}
            for c in store_job.status.conditions
        ],
    }
    print(json.dumps(status, indent=2))
    return 0 if is_succeeded(store_job.status) else 1


if __name__ == "__main__":
    sys.exit(main())
