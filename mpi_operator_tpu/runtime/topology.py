"""Slice topology → jax.sharding.Mesh construction.

The reference has no notion of interconnect topology: MPI ranks are flat and
Horovod's ring is formed at runtime over whatever TCP routes exist (SURVEY.md
§2.5). On TPU the device mesh IS the performance model — collectives along a
mesh axis ride ICI only if that axis maps onto physically adjacent chips —
so mesh construction is a first-class runtime primitive here.

Axis vocabulary (fixed, so every layer — models, trainer, bench — speaks the
same names):

- ``data``      batch sharding (pure DP; gradient psum ≙ Horovod allreduce)
- ``fsdp``      batch + parameter sharding (ZeRO-3-style, rides ICI)
- ``tensor``    megatron-style tensor parallelism (activations all-reduce)
- ``sequence``  context/sequence parallelism (ring attention via ppermute)
- ``expert``    MoE expert parallelism (all_to_all dispatch)
- ``pipe``      pipeline stages (microbatched, ppermute between stages)

A mesh never needs all six: :class:`MeshPlan` names only the axes with size>1
and :func:`build_mesh` lays them out best-ICI-first. Across slices (DCN), the
plan's ``dcn`` sizes produce a hybrid mesh where only the outermost
(gradient-reduction) axes cross the slow network — the scaling-book recipe.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_SEQ = "sequence"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"
AXIS_TENSOR = "tensor"

# Canonical ordering, outermost (cheapest to put on DCN, reduced least often)
# to innermost (hottest collectives, must sit on shortest ICI paths). This is
# the order build_mesh lays axes onto the physical device array.
MESH_AXES: Tuple[str, ...] = (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_EXPERT,
    AXIS_SEQ,
    AXIS_TENSOR,
)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Logical mesh layout: axis name → size. ``dcn`` gives the per-axis
    slice-count for multi-slice (DCN-spanning) meshes; only leading axes may
    cross DCN."""

    axes: Dict[str, int] = dataclasses.field(default_factory=dict)
    dcn: Dict[str, int] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        for name in list(self.axes) + list(self.dcn):
            if name not in MESH_AXES:
                raise ValueError(
                    f"unknown mesh axis {name!r}; the vocabulary is {MESH_AXES}"
                )

    @property
    def ici_size(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1

    @property
    def dcn_size(self) -> int:
        return math.prod(self.dcn.values()) if self.dcn else 1

    @property
    def total_devices(self) -> int:
        return self.ici_size * self.dcn_size

    def ordered(self) -> Tuple[Tuple[str, int], ...]:
        """All axes in canonical order with combined (dcn*ici) sizes."""
        out = []
        for name in MESH_AXES:
            size = self.axes.get(name, 1) * self.dcn.get(name, 1)
            if size > 1 or name in self.axes or name in self.dcn:
                out.append((name, size))
        if not out:
            out.append((AXIS_DATA, 1))
        return tuple(out)

    @staticmethod
    def data_parallel(n: int) -> "MeshPlan":
        return MeshPlan(axes={AXIS_DATA: n})

    @staticmethod
    def parse(spec: str, dcn: str = "") -> "MeshPlan":
        """Parse a parallelism spec from a job manifest / env var —
        ``"fsdp=4,tensor=2"`` (ICI axes) plus an optional DCN spec like
        ``"data=2"`` (slice counts on leading axes). This is how a TPUJob
        chooses non-DP parallelism without code: the worker passes the
        parsed plan to mesh_from_context (e.g. examples/llama_worker.py's
        LLAMA_MESH)."""

        def parse_axes(s: str) -> Dict[str, int]:
            out: Dict[str, int] = {}
            for part in (p.strip() for p in s.split(",") if p.strip()):
                name, _, size = part.partition("=")
                name = name.strip()
                if name in out:
                    raise ValueError(f"duplicate mesh axis {name!r} in {s!r}")
                try:
                    out[name] = int(size)
                except ValueError:
                    raise ValueError(
                        f"bad mesh spec entry {part!r}; expected axis=N"
                    ) from None
                if out[name] < 1:
                    raise ValueError(f"bad mesh axis size in {part!r}")
            return out

        return MeshPlan(axes=parse_axes(spec), dcn=parse_axes(dcn))


def _cpu_or_flat_mesh(shape: Sequence[int], devices) -> np.ndarray:
    return np.asarray(devices).reshape(tuple(shape))


def _hybrid_flat_mesh(
    ici_shape: Sequence[int], dcn_shape: Sequence[int], devices
) -> np.ndarray:
    """Hybrid mesh layout for backends without physical topology (CPU/tests).

    Same device-placement contract as mesh_utils.create_hybrid_device_mesh:
    devices arrive slice-major (slice i owns the i-th contiguous block of
    ici_size devices), and each logical axis of combined size dcn*ici is
    laid out [dcn, ici] with the DCN factor outermost — so a collective
    along an axis with dcn==1 never leaves its slice, and gradient
    reductions along the leading (dcn>1) axes are the only DCN traffic."""
    n = len(ici_shape)
    arr = np.asarray(devices).reshape(tuple(dcn_shape) + tuple(ici_shape))
    perm = [a for i in range(n) for a in (i, n + i)]
    arr = arr.transpose(perm)
    return arr.reshape(tuple(d * i for d, i in zip(dcn_shape, ici_shape)))


def build_mesh(plan: MeshPlan, devices: Optional[Sequence] = None):
    """Materialize the plan as a ``jax.sharding.Mesh``.

    On TPU backends this delegates to ``mesh_utils.create_device_mesh`` (and
    ``create_hybrid_device_mesh`` when the plan spans DCN), which permutes
    devices so that the innermost logical axes land on physical ICI rings.
    On CPU/emulated backends (tests, the driver's virtual 8-device mesh) the
    device list is reshaped row-major — there is no physical topology to
    optimize.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    names = tuple(n for n, _ in plan.ordered())
    sizes = tuple(s for _, s in plan.ordered())
    total = math.prod(sizes)
    if total != len(devices):
        raise ValueError(
            f"mesh plan wants {total} devices ({dict(plan.ordered())}) but "
            f"{len(devices)} are visible — gang placement and plan disagree"
        )

    platform = getattr(devices[0], "platform", "cpu")
    if plan.dcn_size > 1:
        ici_shape = [plan.axes.get(n, 1) for n in names]
        dcn_shape = [plan.dcn.get(n, 1) for n in names]
        if platform == "tpu":
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices
            )
        else:
            # emulated slices: same layout contract, no topology to optimize
            dev_array = _hybrid_flat_mesh(ici_shape, dcn_shape, devices)
    elif platform == "tpu":
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    else:
        dev_array = _cpu_or_flat_mesh(sizes, devices)
    return Mesh(dev_array, names)


def mesh_from_context(
    ctx,
    plan: Optional[MeshPlan] = None,
):
    """Build the job-wide mesh for a bootstrapped host.

    With no explicit plan, defaults to pure data parallelism over every chip
    in the slice — the moral equivalent of the reference's Horovod ring over
    all ranks (examples/horovod/tensorflow_mnist.py, SURVEY.md §2.5). For a
    multi-slice gang (ctx.num_slices > 1) the default is data parallelism
    with the slice count on the DCN factor of the data axis, so gradient
    reductions are the only cross-slice traffic.

    Fails fast when the gang the controller declared (num_hosts ×
    chips_per_host) disagrees with what XLA sees after rendezvous — the
    TPU-side analogue of mpirun's "not enough slots" error; without it a
    worker with mangled env would silently train on a local-only mesh.
    """
    import jax

    if ctx is not None and ctx.chips_per_host:
        expected = ctx.num_hosts * ctx.chips_per_host
        if expected != jax.device_count():
            raise RuntimeError(
                f"gang declares {ctx.num_hosts} hosts × {ctx.chips_per_host} "
                f"chips = {expected} devices but XLA sees "
                f"{jax.device_count()} — rendezvous and placement disagree"
            )
    ns = getattr(ctx, "num_slices", 1) if ctx is not None else 1
    if plan is None:
        n = jax.device_count()
        if ns > 1 and n % ns == 0:
            plan = MeshPlan(axes={AXIS_DATA: n // ns}, dcn={AXIS_DATA: ns})
        else:
            plan = MeshPlan.data_parallel(n)
    elif ns > 1 and plan.dcn_size != ns:
        # an explicit plan on a multi-slice gang MUST name the DCN factor:
        # silently flattening the slices would let inner mesh axes span the
        # slice boundary and put per-layer collectives on DCN instead of
        # ICI — the invariant this module exists to uphold
        raise ValueError(
            f"gang spans {ns} slices but the mesh plan's DCN factor is "
            f"{plan.dcn_size}; declare it (e.g. LLAMA_MESH_DCN='data={ns}')"
        )
    return build_mesh(plan)
