"""Per-step goodput telemetry: stall-attributed wall-second buckets for
the training loop (the workload telemetry plane, ISSUE 15).

The reference operator treats the training process as an opaque
``mpirun`` (PAPER.md §1 layer 1): it can say a job is Running, never WHY
it is slow. This module is the worker-side half of the eyes: a
:class:`StepStatsRecorder` the step loop (ops/elastic.py) threads through
its phases so every wall-second of every step classifies into exactly one
attributed bucket of :data:`~mpi_operator_tpu.machinery.objects.TRAIN_BUCKETS`:

- ``compile``  — the first compute dispatch (trace + XLA compile + run);
- ``input``    — waiting on ``next(batches)`` (the input pipeline);
- ``compute``  — the jitted step (dispatch + the implicit block on the
  previous step's donated buffers: steady-state device time);
- ``sync``     — the gang-uniform membership/preemption allgather;
- ``ckpt``     — checkpoint saves (periodic and forced).

The recorder accumulates cumulative per-incarnation totals plus a rolling
step-time window, and flushes a BOUNDED blob (``bounded_train_stats``,
oplint OBS004) to the file named by ``$TPUJOB_STEPSTATS_FILE`` via atomic
replace. The EXECUTOR owns that env (it points into its log dir) and
polls the file, mirroring the blob into ``pod.status.train_stats``
through the same ``patch_pod_status``/StatusBatcher path ``serve_stats``
rides — workers never need store credentials, exactly like the kubelet
reading cAdvisor. The controller-side goodput aggregator
(controller/goodput.py) rolls the per-pod blobs up into per-job goodput,
dominant-stall attribution and straggler detection.

Overhead budget: two ``perf_counter`` calls per phase plus one dict add —
single-digit microseconds per step against millisecond-scale steps; the
goodput bench (BENCH_CP_MODES=goodput) pins the measured per-step cost at
<=2% of the real llama step p50.

``python -m mpi_operator_tpu.runtime.stepstats --smoke`` is the <30s
verify-gate check: one hollow gang with a seeded input-stall timeline
must roll up to dominant bucket ``input``, and a seeded straggler worker
must fire the skew Event naming its exact pod and node.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import time
from typing import Any, Dict, Optional

from mpi_operator_tpu.machinery.objects import (
    TRAIN_BUCKETS,
    bounded_train_stats,
)

log = logging.getLogger("tpujob.stepstats")

# the executor→worker contract: where the worker flushes its stats blob
# (the executor sets it into the pod env at launch, pointing into its own
# log dir, and polls the file to mirror pod.status.train_stats)
ENV_STATS_FILE = "TPUJOB_STEPSTATS_FILE"
ENV_STATS_INTERVAL = "TPUJOB_STEPSTATS_INTERVAL"
DEFAULT_FLUSH_INTERVAL = 1.0


class StepStatsRecorder:
    """Accumulates per-step bucket attribution inside a training loop.

    Usage (the shape ops/elastic.py wires)::

        stats = StepStatsRecorder.from_env()
        with stats.phase("input"):
            batch = next(batches)
        with stats.phase("compute"):     # first compute → "compile"
            state, m = trainer.train_step(state, batch)
        stats.step_done(step)

    ``clock`` is injectable for deterministic tests. A recorder with no
    path still accumulates (callers read :meth:`snapshot`) but never
    touches the filesystem.
    """

    def __init__(self, path: str = "", *, interval: Optional[float] = None,
                 window: int = 64, clock=time.perf_counter):
        self.path = path or ""
        self.interval = (DEFAULT_FLUSH_INTERVAL if interval is None
                         else max(0.0, interval))
        self._clock = clock
        self._buckets: Dict[str, float] = {k: 0.0 for k in TRAIN_BUCKETS}
        self._step = 0    # global step (checkpoint-resumed jobs pass it in)
        self._steps = 0   # steps run by THIS incarnation (resets on restart)
        self._times: collections.deque = collections.deque(maxlen=window)
        self._step_start = clock()
        self._compiled = False
        self._profile: Optional[Dict[str, str]] = None
        self._last_flush = 0.0
        self._warned = False

    @classmethod
    def from_env(cls, env=None) -> "StepStatsRecorder":
        env = os.environ if env is None else env
        try:
            interval = float(env.get(ENV_STATS_INTERVAL, "") or
                             DEFAULT_FLUSH_INTERVAL)
        except ValueError:
            interval = DEFAULT_FLUSH_INTERVAL
        return cls(env.get(ENV_STATS_FILE, ""), interval=interval)

    @property
    def enabled(self) -> bool:
        return bool(self.path)

    @contextlib.contextmanager
    def phase(self, bucket: str):
        """Attribute the enclosed wall time to ``bucket``. The FIRST
        ``compute`` phase lands in ``compile`` instead: the first step's
        wall time is trace+compile+run, and charging it to compute would
        poison every small-N step average (the 75-98s restart warmup
        ROADMAP item 5 is chasing must be visible as ITS OWN bucket)."""
        t0 = self._clock()
        try:
            yield
        finally:
            dt = self._clock() - t0
            if bucket == "compute" and not self._compiled:
                self._compiled = True
                bucket = "compile"
            self._buckets[bucket] = self._buckets.get(bucket, 0.0) + dt

    def step_done(self, step: Optional[int] = None) -> None:
        """One step finished: record its wall time (everything since the
        previous ``step_done``, untracked loop overhead included) and
        flush if the cadence says so."""
        now = self._clock()
        self._times.append((now - self._step_start) * 1e3)
        self._step_start = now
        self._steps += 1
        self._step = self._step + 1 if step is None else int(step)
        if self.path and now - self._last_flush >= self.interval:
            self.flush(now=now)

    def set_profile(self, req_id: str, state: str, directory: str) -> None:
        """Record the on-demand profile ack (rides the blob so the
        operator side sees capture progress through pod status). Flushed
        immediately: profile transitions are rare and the requester is
        polling for exactly this."""
        self._profile = {"id": req_id, "state": state, "dir": directory}
        if self.path:
            self.flush(force=True)

    def step_p50_ms(self) -> float:
        if not self._times:
            return 0.0
        ordered = sorted(self._times)
        return ordered[len(ordered) // 2]

    def snapshot(self) -> Dict[str, Any]:
        """The bounded blob (exactly what lands in status.train_stats)."""
        from mpi_operator_tpu.runtime import compile_cache

        return bounded_train_stats(
            step=self._step, steps=self._steps,
            step_p50_ms=self.step_p50_ms(), buckets=self._buckets,
            profile=self._profile,
            # present only when the persistent compile cache is on for
            # this process (ISSUE 16) — lets the operator side read the
            # `compile` bucket as warm-vs-cold instead of just big-vs-small
            compile_cache=(compile_cache.cache_stats()
                           if compile_cache.is_configured() else None),
        )

    def flush(self, force: bool = False, now: Optional[float] = None) -> None:
        if not self.path:
            return
        now = self._clock() if now is None else now
        if not force and now - self._last_flush < self.interval:
            return
        self._last_flush = now
        payload = self.snapshot()
        payload["pid"] = os.getpid()
        payload["t"] = time.time()
        try:
            tmp = f"{self.path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)  # readers never see a torn blob
        except OSError:
            if not self._warned:
                # a full disk must not take the training loop down; one
                # warning, then silence (the mirror just goes stale)
                self._warned = True
                log.warning("step-stats flush to %s failed", self.path,
                            exc_info=True)

    def close(self) -> None:
        if self.path:
            self.flush(force=True)


def read_stats(path: str) -> Optional[Dict[str, Any]]:
    """Read a flushed stats blob; None when absent/unreadable/partial
    (the atomic replace makes 'partial' near-impossible, but a reader
    must never crash an executor loop on a torn file)."""
    try:
        with open(path, encoding="utf-8") as f:
            out = json.load(f)
    except (OSError, ValueError):
        return None
    return out if isinstance(out, dict) else None


# ---------------------------------------------------------------------------
# the verify-gate smoke
# ---------------------------------------------------------------------------


def smoke() -> int:
    """<30s goodput smoke: one hollow gang with a seeded INPUT-stall
    timeline must produce dominant bucket ``input`` in its job rollup,
    and a second gang's seeded straggler worker must fire the skew Event
    naming the exact pod and node. Prints one JSON line; exit 0 iff every
    bar held."""
    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.controller.controller import (
        ControllerOptions,
        TPUJobController,
    )
    from mpi_operator_tpu.controller.goodput import GoodputAggregator
    from mpi_operator_tpu.executor.hollow import (
        HollowFleet,
        HollowTimeline,
        TrainLoadModel,
    )
    from mpi_operator_tpu.machinery.events import EventRecorder
    from mpi_operator_tpu.machinery.store import ObjectStore
    from mpi_operator_tpu.scheduler.gang import GangScheduler

    t0 = time.time()
    store = ObjectStore()
    recorder = EventRecorder(store)
    ctrl = TPUJobController(store, recorder, ControllerOptions(threadiness=2))
    sched = GangScheduler(store, recorder)
    train = TrainLoadModel(step_ms=20.0, compile_s=0.2, seed=7)
    train.set_stall("default/stall", "input", 0.7)
    train.set_straggler("default/skew-worker-1", 3.0)
    fleet = HollowFleet(
        store, 2,
        timeline=HollowTimeline(run_s=60.0, train=train,
                                train_stats_interval_s=0.1),
        capacity_chips=8, heartbeat_interval=0.5,
    )
    agg = GoodputAggregator(store, recorder, interval=0.1)
    out: Dict[str, Any] = {"metric": "stepstats_smoke", "ok": False}
    try:
        ctrl.run()
        sched.start()
        fleet.start()
        agg.start()
        client = TPUJobClient(store)
        for name, workers in (("stall", 2), ("skew", 3)):
            client.create({
                "kind": "TPUJob", "metadata": {"name": name},
                "spec": {
                    "slice": {"accelerator": "cpu", "chips_per_host": 1},
                    "worker": {"replicas": workers, "template": {
                        "containers": [{"image": "x",
                                        "command": ["train"]}]}},
                },
            })

        def telemetry(name):
            job = store.try_get("TPUJob", "default", name)
            return (job.status.train_telemetry or {}) if job else {}

        deadline = time.time() + 25.0
        dominant = straggler = ""
        while time.time() < deadline:
            dominant = telemetry("stall").get("dominant_stall", "")
            straggler = telemetry("skew").get("straggler", "")
            if dominant == "input" and straggler:
                break
            time.sleep(0.1)
        out["dominant_stall"] = dominant
        out["straggler"] = straggler
        out["goodput_stall"] = telemetry("stall").get("goodput")
        out["goodput_skew"] = telemetry("skew").get("goodput")
        # the skew Event must name the exact pod AND its node
        pod = store.try_get("Pod", "default", "skew-worker-1")
        node = pod.spec.node_name if pod else ""
        events = [
            e for e in store.list("Event")
            if e.reason == "Straggler"
            and "skew-worker-1" in e.message and node and node in e.message
        ]
        out["skew_event"] = bool(events)
        out["event_message"] = events[0].message if events else ""
        out["elapsed_s"] = round(time.time() - t0, 1)
        out["ok"] = bool(
            dominant == "input"
            and straggler.startswith("default/skew-worker-1")
            and events
            and 0.0 < (out["goodput_stall"] or 0.0) < 1.0
        )
        print(json.dumps(out), flush=True)
        return 0 if out["ok"] else 1
    finally:
        agg.stop()
        fleet.stop()
        sched.stop()
        ctrl.stop()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpu-stepstats",
        description="Workload step-stats plumbing (see module docstring); "
                    "--smoke runs the verify-gate goodput check.",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="<30s goodput smoke: seeded input-stall hollow "
                         "gang → dominant bucket 'input'; seeded "
                         "straggler → skew Event naming pod+node")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    if args.smoke:
        return smoke()
    ap.print_help()
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
