"""SPMD boot + coordinator rendezvous (≙ mpirun/orted/SSH wireup).

The reference's bootstrap is rank-spawn: the launcher's ``mpirun`` reads a
hostfile and ssh-es into each worker to start ``orted``
(/root/reference/v2/pkg/controller/mpi_job_controller.go:176-200, SURVEY.md
§3.3). On TPU the bootstrap is inverted (SURVEY.md §7 "hard parts"): every
host boots the same program; rendezvous is a coordinator handshake
(``jax.distributed``), after which ``jax.devices()`` spans the whole slice and
XLA collectives ride ICI.

The handshake inputs come from the ``TPUJOB_*`` env the controller injects
into every worker pod (controller/controller.py ENV_*), which is this
framework's replacement for ``OMPI_MCA_orte_default_hostfile`` /
``I_MPI_HYDRA_HOST_FILE``.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Mapping, Optional, Tuple

log = logging.getLogger(__name__)

# Env names are deliberately duplicated from controller/controller.py: worker
# images ship only the runtime package, so bootstrap cannot import the
# controller. tests/test_runtime.py asserts both copies stay identical.
ENV_JOB_NAME = "TPUJOB_NAME"
ENV_NAMESPACE = "TPUJOB_NAMESPACE"
ENV_COORDINATOR = "TPUJOB_COORDINATOR_ADDRESS"
ENV_NUM_HOSTS = "TPUJOB_NUM_HOSTS"
ENV_HOST_ID = "TPUJOB_HOST_ID"
ENV_CHIPS_PER_HOST = "TPUJOB_CHIPS_PER_HOST"
ENV_ACCELERATOR = "TPUJOB_ACCELERATOR"
ENV_TOPOLOGY = "TPUJOB_TOPOLOGY"
ENV_HOST_MESH = "TPUJOB_HOST_MESH"
ENV_HOST_COORD = "TPUJOB_HOST_COORD"
ENV_SLICE_ID = "TPUJOB_SLICE_ID"
ENV_NUM_SLICES = "TPUJOB_NUM_SLICES"
# node-local mount point of the cluster's SHARED checkpoint volume, stamped
# by the node agent (--ckpt-dir). A restarted gang can land on different
# nodes, so checkpoints must never live on a node-local path the next
# incarnation cannot see; workloads derive a per-job dir from this via
# default_checkpoint_dir() instead of hardcoding node paths in manifests.
ENV_CKPT_DIR = "TPUJOB_CKPT_DIR"


def _parse_shape(s: str) -> Tuple[int, ...]:
    return tuple(int(p) for p in s.split("x")) if s else ()


@dataclasses.dataclass(frozen=True)
class RuntimeContext:
    """One host's view of the gang — everything the reference smeared across
    hostfile + env + pod identity, in one immutable record."""

    job_name: str = "local"
    namespace: str = "default"
    coordinator_address: str = ""
    num_hosts: int = 1
    host_id: int = 0
    chips_per_host: int = 0  # 0 = undeclared; local_chips() discovers
    accelerator: str = "cpu"
    topology: Tuple[int, ...] = ()
    host_mesh: Tuple[int, ...] = ()
    host_coord: Tuple[int, ...] = ()
    slice_id: int = 0
    num_slices: int = 1

    @property
    def is_distributed(self) -> bool:
        return self.num_hosts > 1

    def local_chips(self) -> int:
        """Declared chips per host, or (when the controller didn't declare —
        local dev runs) whatever XLA actually attached to this host."""
        if self.chips_per_host:
            return self.chips_per_host
        import jax

        return jax.local_device_count()

    @property
    def is_coordinator(self) -> bool:
        """Host 0 absorbs the reference's launcher role (SURVEY.md §7 phase 3:
        the Launcher/Worker split collapses; host 0's exit status is the
        job's)."""
        return self.host_id == 0


def context_from_env(environ: Optional[Mapping[str, str]] = None) -> RuntimeContext:
    """Build the host's RuntimeContext from controller-injected env.

    Absent env falls back to a single-host local context, so the same training
    script runs unmodified on a dev machine (the reference has no analogue —
    an MPIJob image cannot run outside ``mpirun``)."""
    env = os.environ if environ is None else environ
    return RuntimeContext(
        job_name=env.get(ENV_JOB_NAME, "local"),
        namespace=env.get(ENV_NAMESPACE, "default"),
        coordinator_address=env.get(ENV_COORDINATOR, ""),
        num_hosts=int(env.get(ENV_NUM_HOSTS, "1")),
        host_id=int(env.get(ENV_HOST_ID, "0")),
        chips_per_host=int(env.get(ENV_CHIPS_PER_HOST, "0") or 0),
        accelerator=env.get(ENV_ACCELERATOR, "cpu"),
        topology=_parse_shape(env.get(ENV_TOPOLOGY, "")),
        host_mesh=_parse_shape(env.get(ENV_HOST_MESH, "")),
        host_coord=_parse_shape(env.get(ENV_HOST_COORD, "")),
        slice_id=int(env.get(ENV_SLICE_ID, "0") or 0),
        num_slices=int(env.get(ENV_NUM_SLICES, "1") or 1),
    )


def default_checkpoint_dir(
    ctx: RuntimeContext,
    environ: Optional[Mapping[str, str]] = None,
) -> Optional[str]:
    """Per-job checkpoint directory on the shared checkpoint volume the
    node agent advertised (``TPUJOB_CKPT_DIR``), or None when no volume is
    configured. ``<base>/<namespace>/<job>``: namespaced so two tenants'
    jobs of the same name never collide, job-derived so a restarted gang
    RE-PLACED ONTO DIFFERENT NODES resumes from the same path — the
    property the reference inherits from PVCs mounted at a fixed path in
    every worker pod (mpi_job_controller.go:817-877 just runs the template;
    kubernetes mounts the same claim everywhere)."""
    env = os.environ if environ is None else environ
    base = env.get(ENV_CKPT_DIR, "")
    if not base:
        return None
    return os.path.join(base, ctx.namespace, ctx.job_name)


_initialized_ctx: Optional[RuntimeContext] = None


def initialize(
    ctx: Optional[RuntimeContext] = None,
    *,
    environ: Optional[Mapping[str, str]] = None,
) -> RuntimeContext:
    """Rendezvous with the gang. Idempotent; returns the active context.

    Single-host contexts skip the distributed handshake entirely (≙ running
    ``mpirun -n 1`` without any hostfile). Multi-host contexts call
    ``jax.distributed.initialize`` — the coordinator (host 0) binds the port
    the controller advertised via the headless service DNS name; every other
    host dials it. This is the TPU-native replacement for the v2 SSH wireup
    (SURVEY.md §3.3) and the v1 kubectl-exec path (§3.4).
    """
    global _initialized_ctx
    if _initialized_ctx is not None:
        return _initialized_ctx
    if ctx is None:
        ctx = context_from_env(environ)
    # the persistent-compile-cache contract (ISSUE 16): when the executor
    # injected a node-local cache dir, point jax at it BEFORE anything
    # compiles — a relaunched gang then reads its executables off disk
    # instead of repaying the 75–98 s warmup
    from mpi_operator_tpu.runtime import compile_cache

    compile_cache.configure_from_env(environ)
    if ctx.is_distributed:
        import jax

        if not ctx.coordinator_address:
            raise RuntimeError(
                f"{ENV_NUM_HOSTS}={ctx.num_hosts} but {ENV_COORDINATOR} is "
                "unset — the controller always injects both; refusing to guess"
            )
        log.info(
            "rendezvous: job=%s host %d/%d coordinator=%s",
            ctx.job_name,
            ctx.host_id,
            ctx.num_hosts,
            ctx.coordinator_address,
        )
        if ctx.accelerator in ("", "cpu"):
            # cross-process collectives on the CPU backend need the gloo
            # implementation selected BEFORE the distributed handshake —
            # without it every multi-process jit (and orbax's process-sync
            # barrier, so any multi-host checkpoint/restore) dies with
            # "Multiprocess computations aren't implemented on the CPU
            # backend". Newer jax makes gloo the default; the guard keeps
            # this a no-op there.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            # oplint: disable=EXC001 — newer jax removed the knob because
            # gloo IS the default there; the no-op is the desired outcome
            except Exception:
                pass
        jax.distributed.initialize(
            coordinator_address=ctx.coordinator_address,
            num_processes=ctx.num_hosts,
            process_id=ctx.host_id,
        )
    _initialized_ctx = ctx
    return ctx


def active_context() -> Optional[RuntimeContext]:
    return _initialized_ctx


def _reset_for_tests() -> None:
    global _initialized_ctx
    _initialized_ctx = None
