"""Persistent XLA compilation cache: warm restarts skip the warmup
(the workload speed layer, ISSUE 16).

Every recovery path the operator optimizes — gang restart, elastic
rescale, checkpoint-then-migrate, autoscaler cold start — relaunches the
worker process, and the relaunched process repays the full trace+compile
warmup (75–98 s on the real llama/resnet gangs) before its first step.
The program being compiled is byte-identical across incarnations: same
model, same mesh, same jax. jax's persistent compilation cache turns
that repayment into a disk read, IF something owns a cache directory
that survives the pod.

Ownership shape mirrors ``$TPUJOB_STEPSTATS_FILE`` (the telemetry
plane's executor→worker contract): the EXECUTOR owns a node-local cache
dir (stable across incarnations — the whole point) and injects it as
``$TPUJOB_COMPILE_CACHE_DIR`` at launch, gated on the job's
``spec.compile_cache`` knob the controller projects as
``$TPUJOB_COMPILE_CACHE``. The worker side calls
:func:`configure_from_env` at bootstrap (runtime/bootstrap.initialize),
which points jax at a *namespaced* subdir and installs a hit/miss
listener so the telemetry plane can tell a warm restart from a cold one:
:func:`cache_stats` rides the ``compile_cache`` field of the bounded
train_stats blob (machinery/objects.py) into ``pod.status.train_stats``.

Failure modes, by design of jax's cache (verified in
tests/test_compile_cache.py):

- a corrupted/truncated entry is a WARNING + cache miss + fresh compile,
  never a crashed step loop (jax re-writes the entry);
- entries are keyed by a hash covering the jax/jaxlib version, backend
  and compile options, so an upgraded worker can never reuse a stale
  executable — and :func:`cache_namespace` additionally puts each
  (jax version, backend) in its OWN subdir, so mixed-version nodes
  during a rolling upgrade don't even share a directory, and an operator
  can reclaim dead-version caches by deleting the dead subdir;
- an unwritable dir degrades to no caching (jax warns), same contract as
  a full disk on the stepstats flush.

``python -m mpi_operator_tpu.runtime.compile_cache --smoke`` is the <30s
verify-gate check: one tiny jitted workload run twice (two processes,
one cache dir) — the second run must report cache HITS and its
stall-attributed ``compile`` bucket must collapse.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, Mapping, Optional

log = logging.getLogger("tpujob.compilecache")

# the executor→worker contract: the node-local persistent cache root the
# executor owns (stable across pod incarnations, unlike the per-
# incarnation stepstats path — reuse across restarts IS the feature)
ENV_CACHE_DIR = "TPUJOB_COMPILE_CACHE_DIR"
# the controller→executor projection of spec.compile_cache ("1"/"0");
# the executor only injects ENV_CACHE_DIR when this is not "0"
ENV_CACHE_ENABLED = "TPUJOB_COMPILE_CACHE"

# jax's cache-event names (jax._src.monitoring); stable since 0.4.x
_EVENT_HIT = "/jax/compilation_cache/cache_hits"
_EVENT_MISS = "/jax/compilation_cache/cache_misses"

_lock = threading.Lock()
_configured_dir: Optional[str] = None
_listener_installed = False
_counts = {"hits": 0, "misses": 0}


def cache_namespace(jax_version: Optional[str] = None,
                    backend: Optional[str] = None) -> str:
    """The version/backend-scoped subdir name entries live under.

    jax already folds its version + compile options into every cache
    key, so cross-version reuse is impossible at the key level; the
    subdir makes the isolation *inspectable* (an operator can see and
    delete `jax-0.4.36-*` after an upgrade) and keeps a rolling-upgrade
    fleet from churning one directory's eviction LRU from two versions
    at once. Args are injectable for tests; the defaults describe this
    process."""
    if jax_version is None or backend is None:
        import jax

        jax_version = jax_version or jax.__version__
        # default_backend() initializes the platform, which is fine at
        # bootstrap time (the very next thing the worker does is compile)
        backend = backend or jax.default_backend()
    safe = "".join(c if c.isalnum() or c in "._-" else "_"
                   for c in f"{jax_version}-{backend}")
    return f"jax-{safe}"


def _on_event(event: str, **_kw) -> None:
    if event == _EVENT_HIT:
        _counts["hits"] += 1
    elif event == _EVENT_MISS:
        _counts["misses"] += 1


def configure(root: str) -> str:
    """Point jax's persistent compilation cache at
    ``root/<cache_namespace()>`` and start counting hits/misses.
    Idempotent per process (a second call with a different root wins,
    matching jax.config semantics). Returns the namespaced dir."""
    global _configured_dir, _listener_installed
    import jax

    cache_dir = os.path.join(os.path.abspath(root), cache_namespace())
    with _lock:
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            # an unwritable root degrades to no caching (jax will warn on
            # its first write attempt); a worker must never die over it
            log.warning("compile cache dir %s not creatable", cache_dir,
                        exc_info=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache EVERYTHING: the default thresholds skip small/fast
        # compiles, but the restart warmup this exists to kill is the sum
        # of many entries — and the bench's tiny CPU twin would never
        # cross the default 1s floor at all
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        if not _listener_installed:
            from jax._src import monitoring

            monitoring.register_event_listener(_on_event)
            _listener_installed = True
        _configured_dir = cache_dir
    return cache_dir


def configure_from_env(env: Optional[Mapping[str, str]] = None
                       ) -> Optional[str]:
    """Bootstrap-time entry point: configure from ``$TPUJOB_COMPILE_
    CACHE_DIR`` when the executor injected one; a no-op (returns None)
    otherwise, so processes outside the operator keep jax's defaults."""
    env = os.environ if env is None else env
    root = env.get(ENV_CACHE_DIR, "")
    if not root:
        return None
    return configure(root)


def is_configured() -> bool:
    return _configured_dir is not None


def cache_dir() -> Optional[str]:
    return _configured_dir


def cache_stats() -> Dict[str, int]:
    """Cumulative hit/miss counts for THIS process (one incarnation —
    the same reset-on-relaunch contract as the stepstats buckets). A
    warm restart shows hits ≈ entries, misses ≈ 0; a cold start is the
    inverse. Rides the train_stats blob's ``compile_cache`` field."""
    return {"hits": _counts["hits"], "misses": _counts["misses"]}


def _reset_for_tests() -> None:
    global _configured_dir
    with _lock:
        _configured_dir = None
        _counts["hits"] = 0
        _counts["misses"] = 0


# ---------------------------------------------------------------------------
# the verify-gate smoke
# ---------------------------------------------------------------------------

# the child workload: a tiny jitted train-ish step under a
# StepStatsRecorder, so "the compile bucket collapses" is measured by the
# SAME attribution machinery the real step loop flushes
_CHILD_SRC = """
import json, os, sys, time
sys.path.insert(0, {repo!r})
from mpi_operator_tpu.runtime import compile_cache
from mpi_operator_tpu.runtime.stepstats import StepStatsRecorder

compile_cache.configure_from_env()
import jax, jax.numpy as jnp

# unrolled depth so XLA compile time dominates trace time — the smoke's
# warm/cold ratio bar measures the CACHED part (compile), not tracing
@jax.jit
def step(w, x):
    y = x
    for _ in range(8):
        y = jnp.tanh(y @ w) + y
    return w - 1e-3 * (y.T @ y), jnp.sum(y * y)

w = jnp.ones((64, 64), jnp.float32)
x = jnp.ones((8, 64), jnp.float32)
stats = StepStatsRecorder()
for i in range(3):
    with stats.phase("compute"):
        w, loss = step(w, x)
        jax.block_until_ready(loss)
    stats.step_done(i + 1)
blob = stats.snapshot()
print(json.dumps({{"buckets": blob["buckets"],
                   "cache": blob.get("compile_cache")}}))
"""


def smoke() -> int:
    """<30s warm-restart smoke: run the tiny jitted workload twice
    against ONE cache dir (two processes — a restart, not a re-jit).
    Bars: run 1 reports cache misses and no hits (cold); run 2 reports
    hits and zero misses (warm) and its ``compile`` bucket collapses to
    under half of run 1's. Prints one JSON line; exit 0 iff all hold."""
    import subprocess
    import sys
    import tempfile
    import time

    t0 = time.time()
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    out: Dict[str, object] = {"metric": "compile_cache_smoke", "ok": False}
    with tempfile.TemporaryDirectory(prefix="tpujob-cc-smoke-") as root:
        env = dict(os.environ)
        env[ENV_CACHE_DIR] = root
        env.setdefault("JAX_PLATFORMS", "cpu")
        runs = []
        for i in range(2):
            proc = subprocess.run(
                [sys.executable, "-c", _CHILD_SRC.format(repo=repo)],
                env=env, capture_output=True, text=True, timeout=120,
            )
            if proc.returncode != 0:
                out["error"] = proc.stderr[-2000:]
                print(json.dumps(out), flush=True)
                return 1
            runs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        cold, warm = runs
        out["cold_compile_s"] = cold["buckets"]["compile"]
        out["warm_compile_s"] = warm["buckets"]["compile"]
        out["cold_cache"] = cold["cache"]
        out["warm_cache"] = warm["cache"]
        out["elapsed_s"] = round(time.time() - t0, 1)
        out["ok"] = bool(
            cold["cache"] and cold["cache"]["misses"] > 0
            and cold["cache"]["hits"] == 0
            and warm["cache"] and warm["cache"]["hits"] > 0
            and warm["cache"]["misses"] == 0
            and warm["buckets"]["compile"]
            < 0.5 * cold["buckets"]["compile"]
        )
    print(json.dumps(out), flush=True)
    return 0 if out["ok"] else 1


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpu-compile-cache",
        description="Persistent XLA compile cache plumbing (see module "
                    "docstring); --smoke runs the verify-gate warm-"
                    "restart check.",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="<30s warm-restart smoke: tiny jitted workload "
                         "twice against one cache dir; the second run "
                         "must hit the cache and collapse its compile "
                         "bucket")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    if args.smoke:
        return smoke()
    ap.print_help()
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
