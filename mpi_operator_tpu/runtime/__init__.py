"""Multi-host runtime layer: rendezvous, topology, meshes.

This package replaces the reference's entire "MPI runtime & comm backend"
layer (SURVEY.md §1 layer 6): where an MPIJob's launcher runs ``mpirun`` which
ssh-es into workers (/root/reference/v2/pkg/controller/mpi_job_controller.go:176-200)
and ranks talk via OpenMPI/NCCL, a TPUJob's workers all boot the *same* SPMD
program, call :func:`initialize` (coordinator rendezvous, ≙ orted wireup), and
communicate through XLA collectives over ICI/DCN.

There is no per-rank spawn, no hostfile, no SSH: the controller injects the
``TPUJOB_*`` env (controller/controller.py) and this package consumes it.
"""

from mpi_operator_tpu.runtime.bootstrap import (
    RuntimeContext,
    context_from_env,
    initialize,
)
from mpi_operator_tpu.runtime.topology import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_SEQ,
    AXIS_TENSOR,
    MESH_AXES,
    MeshPlan,
    build_mesh,
    mesh_from_context,
)

__all__ = [
    "RuntimeContext",
    "context_from_env",
    "initialize",
    "MeshPlan",
    "build_mesh",
    "mesh_from_context",
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_TENSOR",
    "AXIS_SEQ",
    "AXIS_EXPERT",
    "AXIS_PIPE",
    "MESH_AXES",
]
