"""Local multi-host gang emulation (the envtest trick for the runtime layer).

The reference tests its controller against a real apiserver with *simulated*
pod phases (SURVEY.md §4.2) because no kubelet exists in CI. The equivalent
problem here is testing the multi-host rendezvous + collectives without a
TPU pod slice. Solution: spawn N local OS processes, each pinned to CPU
(``JAX_PLATFORMS=cpu``), each given exactly the ``TPUJOB_*`` env the
controller would inject (controller/controller.py:440-452), all
rendezvousing over localhost TCP via ``jax.distributed``. Real handshake,
real collectives (XLA's CPU ring), zero hardware — N processes ≙ N hosts.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from mpi_operator_tpu.runtime import bootstrap


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def pin_host_device_count(flags: str, n: int) -> str:
    """Return XLA_FLAGS with any inherited host-device-count pin replaced by
    ``n``. Inherited pins (e.g. a test harness's 8-device mesh) would
    otherwise leak into child processes whose declared chip count differs."""
    kept = [
        f
        for f in (flags or "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    kept.append(f"--xla_force_host_platform_device_count={n}")
    return " ".join(kept)


@dataclass
class GangResult:
    returncodes: List[int]
    stdouts: List[str]
    stderrs: List[str]

    @property
    def ok(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)


@dataclass
class LocalGang:
    """Launch ``num_hosts`` copies of a worker script as an SPMD gang.

    This is also what the pi smoke test (examples/pi ≙
    /root/reference/examples/pi/pi.cc) runs under: the same program on every
    host, sum-reduce to host 0, host 0 prints.
    """

    num_hosts: int
    job_name: str = "local-gang"
    chips_per_host: int = 1
    extra_env: Dict[str, str] = field(default_factory=dict)
    timeout: float = 120.0

    def env_for(self, host_id: int, coordinator_port: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                bootstrap.ENV_JOB_NAME: self.job_name,
                bootstrap.ENV_NAMESPACE: "default",
                bootstrap.ENV_COORDINATOR: f"127.0.0.1:{coordinator_port}",
                bootstrap.ENV_NUM_HOSTS: str(self.num_hosts),
                bootstrap.ENV_HOST_ID: str(host_id),
                bootstrap.ENV_CHIPS_PER_HOST: str(self.chips_per_host),
                bootstrap.ENV_ACCELERATOR: "cpu",
                bootstrap.ENV_TOPOLOGY: f"{self.num_hosts * self.chips_per_host}",
                bootstrap.ENV_HOST_MESH: f"{self.num_hosts}",
                bootstrap.ENV_HOST_COORD: str(host_id),
            }
        )
        env["XLA_FLAGS"] = pin_host_device_count(
            env.get("XLA_FLAGS", ""), self.chips_per_host
        )
        return env

    def run(
        self, script: str, args: Sequence[str] = (), cwd: Optional[str] = None
    ) -> GangResult:
        port = free_port()
        procs = []
        for host_id in range(self.num_hosts):
            procs.append(
                subprocess.Popen(
                    [sys.executable, script, *args],
                    env=self.env_for(host_id, port),
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    cwd=cwd,
                )
            )
        # Drain every pipe concurrently: sequential communicate() deadlocks
        # the gang when a later-reaped host fills its pipe buffer mid-
        # collective while an earlier host is still being waited on.
        results: Dict[int, tuple] = {}

        def _reap(i: int, p: subprocess.Popen) -> None:
            try:
                out, err = p.communicate(timeout=self.timeout)
                results[i] = (p.returncode, out, err)
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
                results[i] = (-9, out, err + "\n[gang] timeout, killed")

        threads = [
            threading.Thread(target=_reap, args=(i, p), daemon=True)
            for i, p in enumerate(procs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rcs = [results[i][0] for i in range(self.num_hosts)]
        outs = [results[i][1] for i in range(self.num_hosts)]
        errs = [results[i][2] for i in range(self.num_hosts)]
        return GangResult(returncodes=rcs, stdouts=outs, stderrs=errs)
