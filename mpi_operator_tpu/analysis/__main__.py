"""CLI for the correctness tooling.

    python -m mpi_operator_tpu.analysis lint mpi_operator_tpu tests
    python -m mpi_operator_tpu.analysis lint --format json path/to/file.py
    python -m mpi_operator_tpu.analysis rules
    python -m mpi_operator_tpu.analysis racecheck --selftest
    python -m mpi_operator_tpu.analysis racecheck tests/test_cache.py ...
    python -m mpi_operator_tpu.analysis explore --list
    python -m mpi_operator_tpu.analysis explore dict-rmw --budget 200
    python -m mpi_operator_tpu.analysis explore --replay 'v1:dict-rmw:2=1'
    python -m mpi_operator_tpu.analysis linearize --selftest
    python -m mpi_operator_tpu.analysis linearize history.json ...
    python -m mpi_operator_tpu.analysis fuzz --seed 0 --budget 8
    python -m mpi_operator_tpu.analysis fuzz --replay 'v1:fuzz:5:38,43'
    python -m mpi_operator_tpu.analysis fuzz --selftest
    python -m mpi_operator_tpu.analysis crash --workload 16
    python -m mpi_operator_tpu.analysis crash --list-points
    python -m mpi_operator_tpu.analysis crash --selftest
    python -m mpi_operator_tpu.analysis crash --replica --workload 8
    python -m mpi_operator_tpu.analysis converge
    python -m mpi_operator_tpu.analysis converge --corpus straggler --seed 3
    python -m mpi_operator_tpu.analysis converge --replay 'v1:conv:quota:0:012345'
    python -m mpi_operator_tpu.analysis converge --selftest
    python -m mpi_operator_tpu.analysis authz --probe
    python -m mpi_operator_tpu.analysis authz --probe --backend sqlite
    python -m mpi_operator_tpu.analysis authz --replay 'v1:authz:PUT /v1/objects/{kind}/{ns}/{name}:node:cordon_flip'
    python -m mpi_operator_tpu.analysis authz --selftest

``lint`` exits 1 when any finding survives suppressions (the tier-1 gate
rides this — .claude/skills/verify/SKILL.md). ``racecheck`` without
``--selftest`` delegates to pytest with the plugin armed. ``explore``
runs the deterministic interleaving explorer over a scenario (exit 1 on
a violating schedule, printing its replay token); ``linearize`` checks
recorded store histories against the sequential spec. ``fuzz`` runs the
model-differential store fuzzer over the three real backends (exit 1 on
a divergence, printing its minimal repro + replay token); ``crash`` runs
the ALICE-style crash-point explorer over the SqliteStore commit seam;
``converge`` co-simulates the six control loops over reachable start
states and judges quiescence, write cycles, and wasted-work budgets
(exit 1 on a violation, printing its ``v1:conv:...`` replay token; exit
2 on an unknown corpus, malformed snapshot, or mismatched token).
``authz`` boots a real store fleet (all four token tiers, an open
server, a non-leader follower, the OpsServer monitoring port) and fires
every cell of analysis/authz_policy.json at it, diffing observed
status+error against the declared matrix (exit 1 on a diff, printing
its ``v1:authz:...`` token; exit 2 when the policy itself fails to load
— the loader fails closed on unknown routes/tiers, duplicate keys, and
servable routes with no declaration).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from mpi_operator_tpu.analysis import oplint


def _cmd_lint(args) -> int:
    findings = oplint.lint_paths(args.paths)
    if args.format == "json":
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
    if findings:
        errors = sum(1 for f in findings if f.severity == "error")
        print(
            f"oplint: {len(findings)} finding(s) ({errors} error(s))",
            file=sys.stderr,
        )
        # default gate: ANY finding fails (tier-1 pins the tree to zero);
        # --errors-only is the laxer gate where the severity tier decides
        return 1 if (errors or not args.errors_only) else 0
    print("oplint: clean", file=sys.stderr)
    return 0


def _cmd_rules(args) -> int:
    print(oplint.rule_catalog())
    return 0


def _cmd_racecheck(args) -> int:
    from mpi_operator_tpu.analysis import racecheck

    if args.selftest:
        failures = racecheck.self_test()
        for f in failures:
            print(f"racecheck selftest FAILED: {f}", file=sys.stderr)
        if not failures:
            print("racecheck selftest: ok")
        return 1 if failures else 0
    if not args.pytest_args:
        print("racecheck: pass --selftest or pytest paths/args", file=sys.stderr)
        return 2
    import pytest

    return pytest.main(
        ["-p", "mpi_operator_tpu.analysis.pytest_racecheck", "--racecheck"]
        + args.pytest_args
    )


def _cmd_explore(args) -> int:
    from mpi_operator_tpu.analysis import explore

    if args.list:
        for name in sorted(explore.SCENARIOS):
            s = explore.SCENARIOS[name]
            head = (s.doc or "").strip().splitlines()
            tag = " [seeded-bug]" if s.seeded_bug else ""
            print(f"{name}{tag}")
            if head:
                print(f"  {head[0].strip()}")
        return 0
    if args.replay:
        result = explore.replay(args.replay)
        print(result.message)
        return 0 if result.ok else 1
    names = args.scenario or sorted(explore.SCENARIOS)
    budget = explore.ExploreBudget(
        max_runs=args.budget, max_preemptions=args.preemptions
    )
    rc = 0
    for name in names:
        report = explore.explore(
            name, budget, mode=args.mode, seed=args.seed
        )
        print(report.render())
        seeded = explore.SCENARIOS[name].seeded_bug
        if not report.ok and seeded:
            print(f"  (expected: {name} is a seeded-bug scenario)")
        elif not report.ok:
            rc = 1
        elif seeded:
            # a seeded bug the explorer can no longer find is a DETECTOR
            # regression, the exact inversion of this scenario's contract
            print(
                f"  REGRESSION: seeded-bug scenario {name} found no "
                f"violation within budget",
            )
            rc = 1
    return rc


def _cmd_linearize(args) -> int:
    from mpi_operator_tpu.analysis import linearize

    if args.selftest:
        failures = linearize.self_test()
        for f in failures:
            print(f"linearize selftest FAILED: {f}", file=sys.stderr)
        if not failures:
            print("linearize selftest: ok")
        return 1 if failures else 0
    if not args.histories:
        print("linearize: pass --selftest or history JSON file(s)",
              file=sys.stderr)
        return 2
    rc = 0
    for path in args.histories:
        with open(path, encoding="utf-8") as f:
            history = linearize.History.from_json(f.read())
        report = linearize.check(history)
        print(f"{path}: {report.render()}")
        if not report.ok:
            rc = 1
    return rc


def _cmd_fuzz(args) -> int:
    from mpi_operator_tpu.analysis import storecheck

    if args.selftest:
        failures = storecheck.self_test()
        for f in failures:
            print(f"storecheck selftest FAILED: {f}", file=sys.stderr)
        if not failures:
            print("storecheck selftest: ok")
        return 1 if failures else 0
    if args.replay:
        factories = storecheck.REAL_BACKENDS
        if args.backend:
            factories = {args.backend: storecheck.REAL_BACKENDS[args.backend]}
        rc = 0
        for name, factory in factories.items():
            finding = storecheck.replay(args.replay, factory)
            if finding is None:
                print(f"{name}: token {args.replay} runs clean")
            else:
                print(finding.render())
                rc = 1
        return rc
    budget = storecheck.FuzzBudget(
        sequences=(storecheck.DEFAULT_BUDGET.sequences
                   if args.budget is None else args.budget),
        ops=(storecheck.DEFAULT_BUDGET.ops
             if args.ops is None else args.ops),
    )
    allow_path = storecheck.find_allowlist(os.getcwd())
    allowlist = storecheck.load_allowlist(allow_path) if allow_path else None
    report = storecheck.fuzz(seed=args.seed, budget=budget,
                             allowlist=allowlist)
    print(report.render())
    return 0 if report.ok else 1


def _cmd_crash(args) -> int:
    from mpi_operator_tpu.analysis import crashpoints, storecheck

    if args.selftest:
        failures = crashpoints.self_test()
        for f in failures:
            print(f"crashpoints selftest FAILED: {f}", file=sys.stderr)
        if not failures:
            print("crashpoints selftest: ok")
        return 1 if failures else 0
    if args.replica:
        report = crashpoints.explore_replica(writes=args.workload)
        print(report.render())
        return 0 if report.ok else 1
    if args.list_points:
        snaps, _timeline, _rvs = crashpoints.record(
            crashpoints.commit_heavy_ops(args.workload)
        )
        points = crashpoints.crash_points(snaps, torn=not args.no_torn)
        for pt in points:
            tag = f" torn={pt.torn}" if pt.torn else ""
            print(f"{pt.label}  acked={pt.acked} expected={pt.expected}{tag}")
        print(f"{len(points)} crash point(s)", file=sys.stderr)
        return 0
    allowlist = None
    allow_path = storecheck.find_allowlist(os.getcwd())
    if allow_path:
        allowlist = storecheck.load_allowlist(allow_path)
    report = crashpoints.explore(
        writes=args.workload, torn=not args.no_torn,
        resume=not args.no_resume, allowlist=allowlist,
    )
    print(report.render())
    return 0 if report.ok else 1


def _cmd_converge(args) -> int:
    from mpi_operator_tpu.analysis import convcheck

    try:
        if args.selftest:
            seed = 0 if args.seed is None else args.seed
            failures = convcheck.self_test(seed, log=print)
            for f in failures:
                print(f"convcheck selftest FAILED: {f}", file=sys.stderr)
            if not failures:
                print("convcheck selftest: ok")
            return 1 if failures else 0
        if args.list:
            for cid in sorted(convcheck.CORPORA):
                print(f"{cid}")
                print(f"  {convcheck.CORPORA[cid].description}")
            for mid in sorted(convcheck.MUTANTS):
                m = convcheck.MUTANTS[mid]
                print(f"{mid} [mutant on {m.corpus_id}]")
                print(f"  {m.description}")
            return 0
        snapshot = None
        if args.snapshot:
            snapshot = convcheck.load_snapshot_file(args.snapshot)
        if args.replay:
            # an explicit --corpus/--seed that CONTRADICTS the token is a
            # user error the tool must refuse, not silently pick a winner
            corpus_id, seed, order = convcheck.parse_token(args.replay)
            if args.corpus is not None and args.corpus != corpus_id:
                raise convcheck.TokenError(
                    f"replay token names corpus {corpus_id!r} but "
                    f"--corpus {args.corpus!r} was passed")
            if args.seed is not None and args.seed != seed:
                raise convcheck.TokenError(
                    f"replay token encodes seed {seed} but --seed "
                    f"{args.seed} was passed")
            res = convcheck.run_one(
                corpus_id, seed, order, mutant=args.mutant,
                rounds=args.rounds, snapshot=snapshot)
            print(convcheck.render_result(res))
            return 0 if res.ok else 1
        seed = 0 if args.seed is None else args.seed
        corpora = ([args.corpus] if args.corpus
                   else sorted(convcheck.CORPORA))
        orders = [args.order] if args.order else None
        rc = 0
        for cid in corpora:
            if snapshot is not None or args.order:
                results = [convcheck.run_one(
                    cid, seed, args.order or convcheck._IDENTITY,
                    mutant=args.mutant, rounds=args.rounds,
                    snapshot=snapshot)]
            else:
                results = convcheck.run_corpus(
                    cid, seed, mutant=args.mutant, rounds=args.rounds,
                    orders=orders)
            for res in results:
                print(convcheck.render_result(res))
                if not res.ok:
                    rc = 1
        return rc
    except convcheck.ConvergeError as exc:
        print(f"converge: {exc}", file=sys.stderr)
        return 2


def _cmd_authz(args) -> int:
    from mpi_operator_tpu.analysis import authzcheck

    try:
        if args.selftest:
            failures = authzcheck.self_test(log=print)
            for f in failures:
                print(f"authz selftest FAILED: {f}", file=sys.stderr)
            if not failures:
                print("authz selftest: ok")
            return 1 if failures else 0
        if args.list_mutants:
            for name in sorted(authzcheck.MUTANTS):
                m = authzcheck.MUTANTS[name]
                print(name)
                print(f"  {m.description}")
                # m.token is a v1:authz replay token (a cell address),
                # not a credential
                print(f"  caught by: {m.token}")  # oplint: disable=SEC001
            return 0
        if args.replay:
            finding = authzcheck.replay(
                args.replay, args.backend, mutant=args.mutant
            )
            if finding is None:
                print(f"{args.backend}: token {args.replay} probes clean")
                return 0
            print(finding.render())
            return 1
        # default (and --probe): the full live-server diff the runbook
        # reaches for on a 403/421 storm
        report = authzcheck.probe(
            args.backend, mutant=args.mutant,
            denied_only=args.denied_only, log=print,
        )
        print(report.render())
        return 0 if report.ok else 1
    except authzcheck.AuthzConfigError as exc:
        print(f"authz: {exc}", file=sys.stderr)
        return 2


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi_operator_tpu.analysis", description=__doc__
    )
    sub = ap.add_subparsers(dest="verb", required=True)
    p = sub.add_parser("lint", help="run the oplint ruleset over paths")
    p.add_argument("paths", nargs="+")
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument("--errors-only", action="store_true",
                   help="exit 0 when only warning-severity findings remain "
                        "(default: any finding fails)")
    p.set_defaults(fn=_cmd_lint)
    p = sub.add_parser("rules", help="print the rule catalog")
    p.set_defaults(fn=_cmd_rules)
    p = sub.add_parser(
        "racecheck", help="detector self-test, or pytest under the detector"
    )
    p.add_argument("--selftest", action="store_true")
    # REMAINDER, not "*": pytest flags (-q, -m 'not slow', -x) must reach
    # pytest.main instead of being rejected as unrecognized arguments
    p.add_argument("pytest_args", nargs=argparse.REMAINDER)
    p.set_defaults(fn=_cmd_racecheck)
    p = sub.add_parser(
        "explore",
        help="deterministic interleaving exploration of a scenario "
             "(exit 1 on a violating schedule; its token replays it)",
    )
    p.add_argument("scenario", nargs="*",
                   help="scenario name(s); default: all")
    p.add_argument("--list", action="store_true",
                   help="list scenarios and exit")
    p.add_argument("--replay", metavar="TOKEN",
                   help="re-execute the exact interleaving a token encodes")
    p.add_argument("--budget", type=int, default=80,
                   help="max schedule re-executions (default 80)")
    p.add_argument("--preemptions", type=int, default=2,
                   help="CHESS context bound: forced preemptions per "
                        "schedule (default 2)")
    p.add_argument("--mode", choices=["systematic", "random"],
                   default="systematic")
    p.add_argument("--seed", type=int, default=0,
                   help="rng seed for --mode random")
    p.set_defaults(fn=_cmd_explore)
    p = sub.add_parser(
        "linearize",
        help="check recorded store histories against the sequential spec "
             "(--selftest, or history JSON files)",
    )
    p.add_argument("--selftest", action="store_true")
    p.add_argument("histories", nargs="*")
    p.set_defaults(fn=_cmd_linearize)
    p = sub.add_parser(
        "fuzz",
        help="model-differential fuzz of the three store backends "
             "(exit 1 on a divergence; --replay re-executes its token)",
    )
    p.add_argument("--selftest", action="store_true",
                   help="every seeded mutant caught + real backends clean")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--budget", type=int, default=None,
                   help="sequences per backend (default: "
                        "storecheck.DEFAULT_BUDGET)")
    p.add_argument("--ops", type=int, default=None,
                   help="symbolic ops per sequence (default: "
                        "storecheck.DEFAULT_BUDGET)")
    p.add_argument("--replay", metavar="TOKEN",
                   help="re-execute the exact op subsequence a "
                        "v1:fuzz:<seed>:<ops> token encodes")
    p.add_argument("--backend",
                   choices=["memory", "sqlite", "http", "replica"],
                   help="with --replay: restrict to one backend")
    p.set_defaults(fn=_cmd_fuzz)
    p = sub.add_parser(
        "crash",
        help="ALICE-style crash-point exploration of the SqliteStore "
             "commit seam (exit 1 on a recovery violation)",
    )
    p.add_argument("--selftest", action="store_true",
                   help="real store explores >=50 points clean + seeded "
                        "split-transaction mutant caught")
    p.add_argument("--workload", type=int, default=16, metavar="WRITES",
                   help="committed writes in the commit-heavy workload")
    p.add_argument("--list-points", action="store_true",
                   help="enumerate crash points without checking recovery")
    p.add_argument("--no-torn", action="store_true",
                   help="skip torn-WAL-tail variants")
    p.add_argument("--no-resume", action="store_true",
                   help="skip the per-point ?resource_version= resume check")
    p.add_argument("--replica", action="store_true",
                   help="explore leader-SIGKILL points of a 3-node replica "
                        "set instead (kill-during-log-ship: failover must "
                        "keep every acked write, truncate unacked suffixes)")
    p.set_defaults(fn=_cmd_crash)
    p = sub.add_parser(
        "converge",
        help="closed-loop co-simulation of the six control loops: "
             "quiescence, write cycles, wasted-work budgets (exit 1 on "
             "a violation; its v1:conv token replays it)",
    )
    p.add_argument("--selftest", action="store_true",
                   help="real loops converge on every corpus x order + "
                        "all six seeded mutants caught")
    p.add_argument("--list", action="store_true",
                   help="list corpora and mutants, then exit")
    p.add_argument("--corpus", help="corpus id (default: all)")
    p.add_argument("--seed", type=int, default=None,
                   help="interleaving-enumeration seed (default 0)")
    p.add_argument("--order", metavar="DIGITS",
                   help="run exactly one loop order, e.g. 543210")
    p.add_argument("--rounds", type=int, default=None,
                   help="override the corpus round count")
    p.add_argument("--mutant", help="arm a seeded mutant by id")
    p.add_argument("--replay", metavar="TOKEN",
                   help="re-execute the exact run a v1:conv token encodes "
                        "(refused if --corpus/--seed contradict it)")
    p.add_argument("--snapshot", metavar="PATH",
                   help="start from a snapshot JSON file instead of the "
                        "corpus warmup (fails closed on malformed docs)")
    p.set_defaults(fn=_cmd_converge)
    p = sub.add_parser(
        "authz",
        help="probe a real store fleet against the declared authorization "
             "matrix (exit 1 on a diff; its v1:authz token replays it; "
             "exit 2 when the policy fails closed)",
    )
    p.add_argument("--selftest", action="store_true",
                   help="full matrix clean on memory AND sqlite backings, "
                        "cross-backend parity, all seeded mutants caught "
                        "with deterministic replays, undeclared-route "
                        "injection fails closed")
    p.add_argument("--probe", action="store_true",
                   help="diff the live fleet against authz_policy.json "
                        "(the default when no other mode is given)")
    p.add_argument("--replay", metavar="TOKEN",
                   help="re-probe exactly one matrix cell by its "
                        "v1:authz:<route>:<tier>:<variant> token")
    p.add_argument("--backend", choices=["memory", "sqlite"],
                   default="memory",
                   help="backing store for the probed fleet")
    p.add_argument("--mutant", help="arm a seeded mutant by id")
    p.add_argument("--list-mutants", action="store_true",
                   help="list seeded mutants and the cell that catches "
                        "each, then exit")
    p.add_argument("--denied-only", action="store_true",
                   help="probe only deny/pass cells (the reduced "
                        "state-preserving set tier-1 runs)")
    p.set_defaults(fn=_cmd_authz)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
