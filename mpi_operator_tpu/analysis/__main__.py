"""CLI for the correctness tooling.

    python -m mpi_operator_tpu.analysis lint mpi_operator_tpu tests
    python -m mpi_operator_tpu.analysis lint --format json path/to/file.py
    python -m mpi_operator_tpu.analysis rules
    python -m mpi_operator_tpu.analysis racecheck --selftest
    python -m mpi_operator_tpu.analysis racecheck tests/test_cache.py ...

``lint`` exits 1 when any finding survives suppressions (the tier-1 gate
rides this — .claude/skills/verify/SKILL.md). ``racecheck`` without
``--selftest`` delegates to pytest with the plugin armed.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from mpi_operator_tpu.analysis import oplint


def _cmd_lint(args) -> int:
    findings = oplint.lint_paths(args.paths)
    if args.format == "json":
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
    if findings:
        errors = sum(1 for f in findings if f.severity == "error")
        print(
            f"oplint: {len(findings)} finding(s) ({errors} error(s))",
            file=sys.stderr,
        )
        # default gate: ANY finding fails (tier-1 pins the tree to zero);
        # --errors-only is the laxer gate where the severity tier decides
        return 1 if (errors or not args.errors_only) else 0
    print("oplint: clean", file=sys.stderr)
    return 0


def _cmd_rules(args) -> int:
    print(oplint.rule_catalog())
    return 0


def _cmd_racecheck(args) -> int:
    from mpi_operator_tpu.analysis import racecheck

    if args.selftest:
        failures = racecheck.self_test()
        for f in failures:
            print(f"racecheck selftest FAILED: {f}", file=sys.stderr)
        if not failures:
            print("racecheck selftest: ok")
        return 1 if failures else 0
    if not args.pytest_args:
        print("racecheck: pass --selftest or pytest paths/args", file=sys.stderr)
        return 2
    import pytest

    return pytest.main(
        ["-p", "mpi_operator_tpu.analysis.pytest_racecheck", "--racecheck"]
        + args.pytest_args
    )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi_operator_tpu.analysis", description=__doc__
    )
    sub = ap.add_subparsers(dest="verb", required=True)
    p = sub.add_parser("lint", help="run the oplint ruleset over paths")
    p.add_argument("paths", nargs="+")
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument("--errors-only", action="store_true",
                   help="exit 0 when only warning-severity findings remain "
                        "(default: any finding fails)")
    p.set_defaults(fn=_cmd_lint)
    p = sub.add_parser("rules", help="print the rule catalog")
    p.set_defaults(fn=_cmd_rules)
    p = sub.add_parser(
        "racecheck", help="detector self-test, or pytest under the detector"
    )
    p.add_argument("--selftest", action="store_true")
    # REMAINDER, not "*": pytest flags (-q, -m 'not slow', -x) must reach
    # pytest.main instead of being rejected as unrecognized arguments
    p.add_argument("pytest_args", nargs=argparse.REMAINDER)
    p.set_defaults(fn=_cmd_racecheck)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
