"""CLI for the correctness tooling.

    python -m mpi_operator_tpu.analysis lint mpi_operator_tpu tests
    python -m mpi_operator_tpu.analysis lint --format json path/to/file.py
    python -m mpi_operator_tpu.analysis rules
    python -m mpi_operator_tpu.analysis racecheck --selftest
    python -m mpi_operator_tpu.analysis racecheck tests/test_cache.py ...
    python -m mpi_operator_tpu.analysis explore --list
    python -m mpi_operator_tpu.analysis explore dict-rmw --budget 200
    python -m mpi_operator_tpu.analysis explore --replay 'v1:dict-rmw:2=1'
    python -m mpi_operator_tpu.analysis linearize --selftest
    python -m mpi_operator_tpu.analysis linearize history.json ...

``lint`` exits 1 when any finding survives suppressions (the tier-1 gate
rides this — .claude/skills/verify/SKILL.md). ``racecheck`` without
``--selftest`` delegates to pytest with the plugin armed. ``explore``
runs the deterministic interleaving explorer over a scenario (exit 1 on
a violating schedule, printing its replay token); ``linearize`` checks
recorded store histories against the sequential spec.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from mpi_operator_tpu.analysis import oplint


def _cmd_lint(args) -> int:
    findings = oplint.lint_paths(args.paths)
    if args.format == "json":
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
    if findings:
        errors = sum(1 for f in findings if f.severity == "error")
        print(
            f"oplint: {len(findings)} finding(s) ({errors} error(s))",
            file=sys.stderr,
        )
        # default gate: ANY finding fails (tier-1 pins the tree to zero);
        # --errors-only is the laxer gate where the severity tier decides
        return 1 if (errors or not args.errors_only) else 0
    print("oplint: clean", file=sys.stderr)
    return 0


def _cmd_rules(args) -> int:
    print(oplint.rule_catalog())
    return 0


def _cmd_racecheck(args) -> int:
    from mpi_operator_tpu.analysis import racecheck

    if args.selftest:
        failures = racecheck.self_test()
        for f in failures:
            print(f"racecheck selftest FAILED: {f}", file=sys.stderr)
        if not failures:
            print("racecheck selftest: ok")
        return 1 if failures else 0
    if not args.pytest_args:
        print("racecheck: pass --selftest or pytest paths/args", file=sys.stderr)
        return 2
    import pytest

    return pytest.main(
        ["-p", "mpi_operator_tpu.analysis.pytest_racecheck", "--racecheck"]
        + args.pytest_args
    )


def _cmd_explore(args) -> int:
    from mpi_operator_tpu.analysis import explore

    if args.list:
        for name in sorted(explore.SCENARIOS):
            s = explore.SCENARIOS[name]
            head = (s.doc or "").strip().splitlines()
            tag = " [seeded-bug]" if s.seeded_bug else ""
            print(f"{name}{tag}")
            if head:
                print(f"  {head[0].strip()}")
        return 0
    if args.replay:
        result = explore.replay(args.replay)
        print(result.message)
        return 0 if result.ok else 1
    names = args.scenario or sorted(explore.SCENARIOS)
    budget = explore.ExploreBudget(
        max_runs=args.budget, max_preemptions=args.preemptions
    )
    rc = 0
    for name in names:
        report = explore.explore(
            name, budget, mode=args.mode, seed=args.seed
        )
        print(report.render())
        seeded = explore.SCENARIOS[name].seeded_bug
        if not report.ok and seeded:
            print(f"  (expected: {name} is a seeded-bug scenario)")
        elif not report.ok:
            rc = 1
        elif seeded:
            # a seeded bug the explorer can no longer find is a DETECTOR
            # regression, the exact inversion of this scenario's contract
            print(
                f"  REGRESSION: seeded-bug scenario {name} found no "
                f"violation within budget",
            )
            rc = 1
    return rc


def _cmd_linearize(args) -> int:
    from mpi_operator_tpu.analysis import linearize

    if args.selftest:
        failures = linearize.self_test()
        for f in failures:
            print(f"linearize selftest FAILED: {f}", file=sys.stderr)
        if not failures:
            print("linearize selftest: ok")
        return 1 if failures else 0
    if not args.histories:
        print("linearize: pass --selftest or history JSON file(s)",
              file=sys.stderr)
        return 2
    rc = 0
    for path in args.histories:
        with open(path, encoding="utf-8") as f:
            history = linearize.History.from_json(f.read())
        report = linearize.check(history)
        print(f"{path}: {report.render()}")
        if not report.ok:
            rc = 1
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi_operator_tpu.analysis", description=__doc__
    )
    sub = ap.add_subparsers(dest="verb", required=True)
    p = sub.add_parser("lint", help="run the oplint ruleset over paths")
    p.add_argument("paths", nargs="+")
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument("--errors-only", action="store_true",
                   help="exit 0 when only warning-severity findings remain "
                        "(default: any finding fails)")
    p.set_defaults(fn=_cmd_lint)
    p = sub.add_parser("rules", help="print the rule catalog")
    p.set_defaults(fn=_cmd_rules)
    p = sub.add_parser(
        "racecheck", help="detector self-test, or pytest under the detector"
    )
    p.add_argument("--selftest", action="store_true")
    # REMAINDER, not "*": pytest flags (-q, -m 'not slow', -x) must reach
    # pytest.main instead of being rejected as unrecognized arguments
    p.add_argument("pytest_args", nargs=argparse.REMAINDER)
    p.set_defaults(fn=_cmd_racecheck)
    p = sub.add_parser(
        "explore",
        help="deterministic interleaving exploration of a scenario "
             "(exit 1 on a violating schedule; its token replays it)",
    )
    p.add_argument("scenario", nargs="*",
                   help="scenario name(s); default: all")
    p.add_argument("--list", action="store_true",
                   help="list scenarios and exit")
    p.add_argument("--replay", metavar="TOKEN",
                   help="re-execute the exact interleaving a token encodes")
    p.add_argument("--budget", type=int, default=80,
                   help="max schedule re-executions (default 80)")
    p.add_argument("--preemptions", type=int, default=2,
                   help="CHESS context bound: forced preemptions per "
                        "schedule (default 2)")
    p.add_argument("--mode", choices=["systematic", "random"],
                   default="systematic")
    p.add_argument("--seed", type=int, default=0,
                   help="rng seed for --mode random")
    p.set_defaults(fn=_cmd_explore)
    p = sub.add_parser(
        "linearize",
        help="check recorded store histories against the sequential spec "
             "(--selftest, or history JSON files)",
    )
    p.add_argument("--selftest", action="store_true")
    p.add_argument("histories", nargs="*")
    p.set_defaults(fn=_cmd_linearize)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
