"""Correctness tooling for the control plane (≙ the reference's
golangci-lint gate + `go test -race` CI split):

- :mod:`oplint` — AST rules over this repo's own invariants (RMW001,
  UID001, TERM001, BLK001, EXC001, SEC001), with per-line
  ``# oplint: disable=RULE`` suppressions;
- :mod:`racecheck` — runtime lock-order + unguarded-shared-state detector
  (tracked lock factories + lockset/Eraser attribute monitoring), exposed
  as the opt-in pytest plugin :mod:`pytest_racecheck`.

CLI: ``python -m mpi_operator_tpu.analysis lint mpi_operator_tpu tests``
and ``python -m mpi_operator_tpu.analysis racecheck --selftest``.
"""

from mpi_operator_tpu.analysis.oplint import (
    RULES,
    Finding,
    Rule,
    lint_paths,
    lint_source,
    rule_catalog,
)
from mpi_operator_tpu.analysis.racecheck import (
    LockOrderFinding,
    LockTracker,
    Session,
    SharedStateFinding,
    SharedStateMonitor,
    self_test,
)

__all__ = [
    "RULES", "Rule", "Finding", "lint_paths", "lint_source", "rule_catalog",
    "LockTracker", "LockOrderFinding", "SharedStateFinding",
    "SharedStateMonitor", "Session", "self_test",
]
