"""Correctness tooling for the control plane (≙ the reference's
golangci-lint gate + `go test -race` CI split, now grown into a model-
checking layer):

- :mod:`oplint` — AST rules over this repo's own invariants (RMW001
  through AUTH001 — the full catalog prints via ``rules``), with
  per-line ``# oplint: disable=RULE`` suppressions and a stable
  ``lint --format json`` finding schema;
- :mod:`racecheck` — runtime lock-order + unguarded-shared-state detector
  (tracked lock factories + lockset/Eraser attribute monitoring), exposed
  as the opt-in pytest plugin :mod:`pytest_racecheck`; deliberate
  patterns are declared in ``.racecheck-allow`` with reasons;
- :mod:`explore` — deterministic interleaving explorer (CHESS-style
  bounded preemption over lock + store-op yield points); every failure
  prints a schedule token and ``--replay`` re-executes it exactly;
- :mod:`linearize` — store history recorder + sequential-spec model +
  Porcupine-style linearizability checker, exposed as the opt-in pytest
  plugin :mod:`pytest_linearize`;
- :mod:`model` — the sequential store spec in both executable forms:
  ``StoreModel`` (the validator the linearizability checker prunes on)
  and ``ModelStore`` (the generator reference the differential fuzzer
  diffs against), mechanically pinned to each other;
- :mod:`storecheck` — model-differential fuzzer over all three store
  backends (seeded symbolic op sequences, ddmin-shrunk divergences,
  ``v1:fuzz:<seed>:<ops>`` replay tokens, seeded-mutant selftest,
  pinned repro corpus under ``tests/data/storecheck/``); deliberate
  exceptions are declared in ``.storecheck-allow`` with reasons;
- :mod:`crashpoints` — ALICE-style crash-point explorer over the
  SqliteStore ``_txn`` commit seam (exact + torn-WAL-tail snapshots,
  acked-write durability at exact rv, rv monotonicity across reopen,
  resume-or-410; oplint DUR001 keeps every mutation on the seam);
- :mod:`convcheck` — closed-loop co-simulation of the six control loops
  over reachable start states (quiescence, write-cycle, wasted-work
  budgets; ``v1:conv`` replay tokens; oplint LEV001 keeps handlers
  level-triggered);
- :mod:`authzcheck` — declarative authorization matrix
  (``authz_policy.json``: every (route, verb, tier, scope-variant) →
  expected outcome, loaded fail-closed) probed against a REAL booted
  store fleet — all four token tiers, an open server, a non-leader
  follower, the OpsServer monitoring port — with route coverage
  introspected from the live router, a wire-capture secret scan of
  /metrics, seeded mutants, and ``v1:authz`` replay tokens; oplint
  AUTH001 statically cross-checks route literals and auth-before-state
  ordering against the same matrix.

CLI: ``python -m mpi_operator_tpu.analysis
{lint,rules,racecheck,explore,linearize,fuzz,crash,converge,authz}``.
"""

from mpi_operator_tpu.analysis.oplint import (
    RULES,
    Finding,
    Rule,
    lint_paths,
    lint_source,
    rule_catalog,
)
from mpi_operator_tpu.analysis.racecheck import (
    AllowRule,
    LockOrderFinding,
    LockTracker,
    Session,
    SharedStateFinding,
    SharedStateMonitor,
    load_allowlist,
    parse_allowlist,
    self_test,
)

__all__ = [
    "RULES", "Rule", "Finding", "lint_paths", "lint_source", "rule_catalog",
    "LockTracker", "LockOrderFinding", "SharedStateFinding",
    "SharedStateMonitor", "Session", "self_test",
    "AllowRule", "load_allowlist", "parse_allowlist",
]
