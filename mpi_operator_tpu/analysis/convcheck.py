"""convcheck — convergence & quiescence checking for the six control loops.

A Kubernetes-style operator is a fixed point machine: every controller is
level-triggered, so on a cluster where nothing external changes, the whole
plane must reach a state where NO loop writes anything — and reach it in
bounded work. The unit suites pin each loop's transitions; nothing pins the
**joint** liveness claim. Two individually-correct loops can still fight
(A's fix is B's trigger), a dropped hysteresis guard turns one migration
into a permanent ping-pong, and a status writer that forgets no-op elision
never quiesces at all. Those defects are invisible to per-loop tests and
catastrophic in a fleet.

convcheck closes that gap with a deterministic closed-loop co-simulation:

- the REAL sync functions of the six leader-only loops — TPUJobController,
  TPUServeController, ServeAutoscaler, DrainController, Rescheduler and
  GoodputAggregator — run against a plain in-memory ObjectStore wrapped in
  a write-recording proxy, on a virtual clock. No threads, no sleeps: the
  harness owns the tick order and enumerates seeded loop interleavings.
- start states come from a small corpus of REACHABLE snapshots (built by
  driving the real loops through a scripted warmup): mid-rollout,
  mid-drain, fragmented fleet, straggler-blamed node, quota-saturated
  tenant, autoscale mid-spike.
- three judged properties per run:
  * **quiescence** — once the scripted stimulus freezes, the final rounds
    must see ZERO store writes from any author;
  * **no write cycles** — a canonical state hash (volatile bookkeeping
    stripped) revisiting an earlier value after loop-authored writes is an
    oscillation; the minimal write cycle is printed with each write's
    authoring loop;
  * **bounded wasted work** — store writes per author and requeues per
    controller against per-corpus tripwire budgets.

Every failure prints a deterministic replay token
``v1:conv:<corpus>:<seed>:<order>`` that re-executes the exact run.

The self-test holds the checker to its own bar: six seeded mutants — each
reintroducing a defect class the real loops guard against (hysteresis
removed, stabilization window removed, no-op elision removed, anti-hop
placement removed, alert clear-hold removed, requeue-always) — MUST be
caught, while every REAL loop runs the whole corpus clean.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass, field, replace
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from mpi_operator_tpu.api.client import TPUJobClient, TPUServeClient
from mpi_operator_tpu.api.types import (
    ALERT_NAMESPACE,
    Alert,
    AlertSpec,
    AlertState,
    AlertStatus,
    ObjectMeta,
)
from mpi_operator_tpu.controller import autoscaler as autoscaler_mod
from mpi_operator_tpu.controller.autoscaler import (
    ANNOTATION_OFFERED_QPS,
    ServeAutoscaler,
)
from mpi_operator_tpu.controller.controller import (
    ControllerOptions,
    TPUJobController,
)
from mpi_operator_tpu.controller.disruption import DrainController
from mpi_operator_tpu.controller.goodput import GoodputAggregator
from mpi_operator_tpu.controller.rescheduler import Rescheduler
from mpi_operator_tpu.controller.serve import (
    LABEL_SERVE_NAME,
    TPUServeController,
)
from mpi_operator_tpu.controller.slo_monitor import (
    FIRE,
    RESOLVE,
    BurnPolicy,
    Probe,
)
from mpi_operator_tpu.controller.slo_monitor import step as slo_step
from mpi_operator_tpu.machinery.events import EventRecorder
from mpi_operator_tpu.machinery.objects import (
    ANNOTATION_MAINTENANCE_AT,
    ANNOTATION_STRAGGLER_NODE,
    NODE_NAMESPACE,
    Node,
    PodPhase,
    bounded_train_stats,
)
from mpi_operator_tpu.machinery.scenario import (
    ScenarioError,
    restore_store,
    snapshot_store,
)
from mpi_operator_tpu.machinery.serialize import KIND_CLASSES, encode
from mpi_operator_tpu.machinery.store import ObjectStore
from mpi_operator_tpu.scheduler.gang import GangScheduler

__all__ = [
    "CORPORA",
    "MUTANTS",
    "ConvergeError",
    "CorpusError",
    "TokenError",
    "RunResult",
    "enumerate_orders",
    "format_token",
    "parse_token",
    "replay",
    "run_corpus",
    "self_test",
]

# The co-sim clock. EPOCH sits ABOVE any plausible wall clock so the few
# wall-stamped fields the loops compare against virtual time (condition
# transition times, backoff anchors) read as "long ago" — monotone-sane —
# instead of "in the future".
EPOCH = 2_200_000_000.0
DT = 60.0

LOOPS = ("job", "serve", "autoscaler", "drain", "rescheduler", "goodput")
_IDENTITY = "".join(str(i) for i in range(len(LOOPS)))

LABEL_JOB_NAME = "tpujob.dev/job-name"
LABEL_GENERATION = "tpujob.dev/generation"

# Production-shaped rescheduler knobs at DT-round granularity: the
# hysteresis must outlive a whole run (a gang is migrated for a suspected
# straggler at most ONCE per incident), the sliding window spans one round.
RESCHED_KW = dict(
    hysteresis_s=3600.0,
    window_s=60.0,
    max_moves=2,
    min_gain_chips=2,
    drain_window_s=120.0,
)


class ConvergeError(Exception):
    """Base failure of the convergence checker itself (not a verdict)."""


class CorpusError(ConvergeError):
    """Unknown corpus id, or a snapshot document that fails validation."""


class TokenError(ConvergeError):
    """A malformed or mismatched replay token."""


# ---------------------------------------------------------------------------
# the write-recording store proxy
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class WriteRecord:
    step: int          # global step index (hash points share this axis)
    round: int
    author: str        # loop name, "fleet", "slo", or "setup"
    verb: str
    kind: str
    key: str


class RecordingStore:
    """Transparent ObjectStore proxy tagging every write with the author
    the harness set around the current tick (the CountingStore idiom from
    tests/test_stress.py, extended with attribution)."""

    _WRITE_VERBS = ("create", "update", "delete", "try_delete", "patch")

    def __init__(self, backing: ObjectStore):
        self._backing = backing
        self.author = "setup"
        self.round = -1
        self.step = 0
        self.writes: List[WriteRecord] = []

    def _record(self, verb: str, args: tuple) -> None:
        kind = key = "?"
        if args:
            first = args[0]
            if isinstance(first, str):
                kind = first
                if len(args) >= 3:
                    key = f"{args[1]}/{args[2]}"
            else:  # create/update take the object itself
                kind = getattr(first, "kind", "?")
                meta = getattr(first, "metadata", None)
                if meta is not None:
                    key = f"{meta.namespace}/{meta.name}"
        self.writes.append(WriteRecord(
            self.step, self.round, self.author, verb, kind, key))

    def create(self, *a, **kw):
        self._record("create", a)
        return self._backing.create(*a, **kw)

    def update(self, *a, **kw):
        self._record("update", a)
        return self._backing.update(*a, **kw)

    def delete(self, *a, **kw):
        self._record("delete", a)
        return self._backing.delete(*a, **kw)

    def try_delete(self, *a, **kw):
        self._record("try_delete", a)
        return self._backing.try_delete(*a, **kw)

    def patch(self, *a, **kw):
        self._record("patch", a)
        return self._backing.patch(*a, **kw)

    def patch_batch(self, items):
        for it in items:
            self._record("patch", tuple(it) if isinstance(it, (list, tuple))
                         else (getattr(it, "kind", "?"),))
        return self._backing.patch_batch(items)

    def __getattr__(self, name):
        return getattr(self._backing, name)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for w in self.writes:
            out[w.author] = out.get(w.author, 0) + 1
        return out


# ---------------------------------------------------------------------------
# canonical state hashing
# ---------------------------------------------------------------------------

# Fields that move without the cluster's SEMANTIC state moving: identity
# bookkeeping, timestamps, monotone incident counters, and free-text
# messages (many embed timestamps or elapsed values). Stripping them makes
# a genuine oscillation revisit the same hash instead of hiding behind a
# bumped resource_version.
_VOLATILE_KEYS = frozenset({
    "resource_version", "uid", "creation_timestamp", "owner_references",
    "last_transition_time", "last_heartbeat", "last_probe_time",
    "last_scale_up_time", "last_scale_down_time",
    "since", "resolved_at", "start_time", "completion_time", "timestamp",
    "restart_generation", "restart_count",
    "worst_burn", "burn", "fired_count", "incident", "message",
})


def _scrub(value: Any, parent_key: str = "") -> Any:
    if isinstance(value, dict):
        out = {}
        for k, v in value.items():
            if k in _VOLATILE_KEYS:
                continue
            if parent_key == "annotations":
                if "trace" in k:
                    continue  # trace ids are per-incarnation bookkeeping
                if k == ANNOTATION_STRAGGLER_NODE:
                    out[k] = "1"  # normalize the flag's timestamp payload
                    continue
            if parent_key == "labels" and k == LABEL_GENERATION:
                continue  # monotone per-restart stamp
            out[k] = _scrub(v, k)
        return out
    if isinstance(value, list):
        return [_scrub(v, parent_key) for v in value]
    if isinstance(value, float):
        return round(value, 6)
    return value


def canonical_hash(backing: ObjectStore) -> str:
    doc = []
    for kind in sorted(KIND_CLASSES):
        if kind == "Event":
            continue  # an audit trail, not cluster state
        objs = backing.list(kind)
        objs.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        for obj in objs:
            doc.append([
                kind, obj.metadata.namespace, obj.metadata.name,
                _scrub(encode(obj)),
            ])
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()


# ---------------------------------------------------------------------------
# the SLO participant
# ---------------------------------------------------------------------------


class _SLOShell:
    """The alert plane's seat at the table: the real SLOMonitor's pure
    ``step()`` core driven by scripted burn rates, writing Alert objects
    through the recorded store exactly like the monitor's write path (the
    monitor itself needs an HTTP scraper, which has no place in a co-sim).
    """

    OBJECTIVE = "convcheck-burn"

    def __init__(self, store, policy: Optional[BurnPolicy] = None):
        self.store = store
        self.policy = policy or BurnPolicy()
        self.probe = Probe()

    def tick(self, burns: Optional[Mapping[str, Optional[float]]],
             now: float) -> None:
        if burns is None:
            return
        self.probe, event = slo_step(self.probe, burns, self.policy, now)
        if event == FIRE:
            self._write_state(AlertState.FIRING, now)
        elif event == RESOLVE:
            self._write_state(AlertState.RESOLVED, now)

    def _write_state(self, state: str, now: float) -> None:
        cur = self.store.try_get("Alert", ALERT_NAMESPACE, self.OBJECTIVE)
        if cur is None:
            alert = Alert(
                metadata=ObjectMeta(name=self.OBJECTIVE,
                                    namespace=ALERT_NAMESPACE),
                spec=AlertSpec(objective=self.OBJECTIVE,
                               metric="convcheck_scripted_burn",
                               severity="page",
                               description="convcheck co-sim burn script"),
            )
            alert.status = AlertStatus(
                state=state, window="fast", since=self.probe.since,
                burn=round(self.probe.worst_burn, 3),
                fired_count=self.probe.fired_count,
            )
            self.store.create(alert)
            return
        patch: Dict[str, Any] = {
            "state": state,
            "burn": round(self.probe.worst_burn, 3),
            "fired_count": self.probe.fired_count,
        }
        if state == AlertState.FIRING:
            patch["since"] = self.probe.since
            patch["resolved_at"] = None
        else:
            patch["resolved_at"] = now
        self.store.patch(
            "Alert", ALERT_NAMESPACE, self.OBJECTIVE,
            {"metadata": {"uid": cur.metadata.uid}, "status": patch},
            subresource="status",
        )


# ---------------------------------------------------------------------------
# corpus definitions
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Corpus:
    """One reachable start state plus its scripted environment."""

    id: str
    description: str
    start_round: int                      # warmup occupies [0, start_round)
    rounds: int                           # judged rounds
    seed_objects: Callable[["World"], None]
    stimulus: Optional[Callable[["World", int], None]] = None
    pod_stats: Optional[Callable[["World", Any, int], Optional[dict]]] = None
    burns: Optional[Callable[[int], Optional[Dict[str, float]]]] = None
    finalize: Optional[Callable[["World"], None]] = None
    # tripwire budgets over the judged run (writes per author; 'fleet' is
    # the environment and exempt); requeues per queue-driven controller
    write_budgets: Mapping[str, int] = field(default_factory=dict)
    requeue_budgets: Mapping[str, int] = field(default_factory=dict)


def _mk_node(world: "World", name: str, cap: int,
             annotations: Optional[Dict[str, str]] = None) -> None:
    node = Node()
    node.metadata.namespace = NODE_NAMESPACE
    node.metadata.name = name
    if annotations:
        node.metadata.annotations.update(annotations)
    node.status.ready = True
    node.status.last_heartbeat = 0.0  # static registration: always live
    node.status.capacity_chips = cap
    world.store.create(node)


def _job_manifest(name: str, replicas: int) -> dict:
    return {
        "apiVersion": "tpujob.dev/v1",
        "kind": "TPUJob",
        "metadata": {"name": name},
        "spec": {
            "worker": {
                "replicas": replicas,
                "restart_policy": "OnFailure",
                "template": {"containers": [{
                    "name": "w", "image": "local", "command": ["true"],
                }]},
            },
        },
    }


def _seed_bound_job(world: "World", name: str, placements: Sequence[str],
                    ) -> None:
    """Create a TPUJob and hand-bind its workers (the test_stress 'fake
    scheduler' idiom) so the corpus controls initial placement exactly;
    the real scheduler owns every placement AFTER the snapshot."""
    client = TPUJobClient(world.store)
    client.create(_job_manifest(name, len(placements)))
    world.jobctl.sync_handler(f"default/{name}")
    for i, node_name in enumerate(placements):
        pod = world.store.get("Pod", "default", f"{name}-worker-{i}")
        world.store.patch(
            "Pod", "default", pod.metadata.name,
            {"metadata": {"uid": pod.metadata.uid},
             "spec": {"node_name": node_name}},
        )
        world.store.patch(
            "Pod", "default", pod.metadata.name,
            {"metadata": {"uid": pod.metadata.uid},
             "status": {"phase": PodPhase.RUNNING, "ready": True}},
            subresource="status",
        )
    world.jobctl.sync_handler(f"default/{name}")


def _train_stats(slow_pods: Sequence[str] = (),
                 drift_nodes: Sequence[str] = (),
                 freeze: Optional[int] = None):
    """A kubelet stat script: every running batch worker reports ~100ms
    steps; ``slow_pods`` report a stable-slow 500ms (a sick WORKLOAD —
    moving it cures nothing); pods on ``drift_nodes`` report a drifting
    p50 (sick HARDWARE — moving off the node cures it). Workload step
    progress freezes at round ``freeze``; hardware drift never does."""

    def fn(world: "World", pod, rnd: int) -> Optional[dict]:
        if LABEL_SERVE_NAME in pod.metadata.labels:
            return None
        cur = pod.status.train_stats or {}
        step = int(cur.get("step", 0))
        steps = int(cur.get("steps", 0))
        frozen = freeze is not None and rnd >= freeze
        if not frozen:
            step += 5
            steps += 5
        p50 = 100.0
        if pod.spec.node_name in drift_nodes:
            p50 = 600.0 + 20.0 * rnd
        elif pod.metadata.name in slow_pods:
            p50 = 500.0
        return dict(step=step, steps=steps, step_p50_ms=p50,
                    buckets={"compute": 4.0, "input": 1.0})

    return fn


# -- the six corpora --------------------------------------------------------


def _seed_fragmented(world: "World") -> None:
    for i in (1, 2, 3):
        _mk_node(world, f"f{i}", cap=2)
    for i in (1, 2, 3):
        _seed_bound_job(world, f"frag-{i}", [f"f{i}"])


def _seed_straggler(world: "World") -> None:
    for name, cap in (("n1", 2), ("n2", 2), ("n3", 2), ("n4", 2)):
        _mk_node(world, name, cap)
    # strag's worker-0 sits on the sick node n1; lag's worker-0 is an
    # intrinsically slow WORKLOAD (slow wherever it lands)
    _seed_bound_job(world, "strag", ["n1", "n2"])
    _seed_bound_job(world, "lag", ["n3", "n2"])


def _seed_mid_drain(world: "World") -> None:
    _mk_node(world, "d1", cap=2)
    _mk_node(world, "d2", cap=2)
    _seed_bound_job(world, "evac", ["d1", "d1"])


def _fin_mid_drain(world: "World") -> None:
    node = world.store.get("Node", NODE_NAMESPACE, "d1")
    world.store.patch(
        "Node", NODE_NAMESPACE, "d1",
        {"metadata": {"uid": node.metadata.uid,
                      "annotations": {
                          ANNOTATION_MAINTENANCE_AT: str(EPOCH + 40 * DT),
                      }}},
    )


def _seed_quota(world: "World") -> None:
    _mk_node(world, "q1", cap=2)
    _seed_bound_job(world, "holder", ["q1", "q1"])
    # the saturated tenant: a gang that genuinely does not fit — it must
    # WAIT quietly (no defrag churn, no requeue storm, no event spam)
    client = TPUJobClient(world.store)
    client.create(_job_manifest("waiter", 2))
    world.jobctl.sync_handler("default/waiter")


def _quota_burns(rnd: int) -> Optional[Dict[str, float]]:
    # judged rounds start at 1. Hot burn r1-r2, a flapping tail r3-r6
    # (alternating hot/clean: the shape the clear-hold hysteresis exists
    # for), clean from r7 — the real policy resolves once, ~r12. Training
    # stats freeze at r2, so during the flap the Alert is the ONLY moving
    # object: strip the clear-hold and the FIRING->RESOLVED->FIRING flap
    # revisits an identical canonical state — the minimal write cycle.
    if rnd < 1:
        return None
    if rnd <= 2:
        hot = True
    elif rnd <= 6:
        hot = (rnd % 2 == 1)
    else:
        hot = False
    v = 20.0 if hot else 0.1
    return {"fast_short": v, "fast_long": v,
            "slow_short": v, "slow_long": v}


def _serve_manifest(name: str, replicas: int, autoscale: Optional[dict],
                    ) -> dict:
    doc: Dict[str, Any] = {
        "kind": "TPUServe",
        "metadata": {"name": name},
        "spec": {"replicas": replicas},
    }
    if autoscale is not None:
        doc["spec"]["autoscale"] = autoscale
    return doc


def _seed_mid_rollout(world: "World") -> None:
    TPUServeClient(world.store).create(_serve_manifest("roll", 2, None))


def _fin_mid_rollout(world: "World") -> None:
    serve = world.store.get("TPUServe", "default", "roll")
    world.store.patch(
        "TPUServe", "default", "roll",
        {"metadata": {"uid": serve.metadata.uid},
         "spec": {"template": {"container": {"env": {"MODEL": "v2"}}}}},
    )


def _seed_spike(world: "World") -> None:
    client = TPUServeClient(world.store)
    client.create(_serve_manifest("spiky", 1, {
        "min_replicas": 1,
        "max_replicas": 4,
        "target_qps_per_replica": 300.0,
        "scale_down_stabilization_s": 300.0,
    }))
    serve = world.store.get("TPUServe", "default", "spiky")
    world.store.patch(
        "TPUServe", "default", "spiky",
        {"metadata": {"uid": serve.metadata.uid,
                      "annotations": {ANNOTATION_OFFERED_QPS: "100"}}},
    )


def _spike_stimulus(world: "World", rnd: int) -> None:
    # judged rounds start at 2: the front door oscillates 900/100 through
    # r7, then settles at 100 — the down-stabilization window (300s = 5
    # rounds) is what keeps the real autoscaler from chasing every flip
    if rnd < 2:
        return
    if rnd <= 7:
        qps = "900" if rnd % 2 == 0 else "100"
    else:
        qps = "100"
    serve = world.store.try_get("TPUServe", "default", "spiky")
    if serve is None:
        return
    if serve.metadata.annotations.get(ANNOTATION_OFFERED_QPS) == qps:
        return
    world.store.patch(
        "TPUServe", "default", "spiky",
        {"metadata": {"uid": serve.metadata.uid,
                      "annotations": {ANNOTATION_OFFERED_QPS: qps}}},
    )


CORPORA: Dict[str, Corpus] = {}


def _register(corpus: Corpus) -> None:
    CORPORA[corpus.id] = corpus


_register(Corpus(
    id="fragmented",
    description="three 1-chip gangs pinning three 2-chip nodes: total "
                "free fits another gang but no contiguous block does, and "
                "the defrag gain (1 chip) is under min_gain_chips — the "
                "rescheduler must do NOTHING",
    start_round=1, rounds=10,
    seed_objects=_seed_fragmented,
    pod_stats=_train_stats(freeze=4),
    write_budgets={"job": 2, "serve": 0, "autoscaler": 0, "drain": 0,
                   "rescheduler": 0, "goodput": 12, "slo": 0},
    requeue_budgets={"job": 2, "serve": 0},
))

_register(Corpus(
    id="straggler",
    description="goodput has blamed two gangs: one pinned to drifting-p50 "
                "hardware (a move cures it; rebinding to the flagged node "
                "re-poisons it), one carrying an intrinsically slow "
                "worker (a move cures nothing; hysteresis must park it "
                "after ONE try)",
    start_round=1, rounds=14,
    seed_objects=_seed_straggler,
    pod_stats=_train_stats(slow_pods=("lag-worker-0",),
                           drift_nodes=("n1",), freeze=8),
    write_budgets={"job": 28, "serve": 0, "autoscaler": 0, "drain": 0,
                   "rescheduler": 12, "goodput": 24, "slo": 0},
    requeue_budgets={"job": 4, "serve": 0},
))

_register(Corpus(
    id="mid-drain",
    description="a whole gang sits on a node carrying a fresh maintenance "
                "notice: the drain plane must cordon, migrate the gang "
                "once, mark Drained once, and go silent",
    start_round=1, rounds=12,
    seed_objects=_seed_mid_drain,
    finalize=_fin_mid_drain,
    pod_stats=_train_stats(freeze=6),
    write_budgets={"job": 16, "serve": 0, "autoscaler": 0, "drain": 10,
                   "rescheduler": 2, "goodput": 8, "slo": 0},
    requeue_budgets={"job": 4, "serve": 0},
))

_register(Corpus(
    id="quota",
    description="a capacity-saturated tenant (a pending gang that fits "
                "nowhere) plus a scripted SLO burn that flaps across the "
                "fire threshold: the waiter must wait QUIETLY and the "
                "alert must ride the flap without re-paging",
    start_round=1, rounds=16,
    seed_objects=_seed_quota,
    pod_stats=_train_stats(freeze=2),
    burns=_quota_burns,
    write_budgets={"job": 2, "serve": 0, "autoscaler": 0, "drain": 0,
                   "rescheduler": 2, "goodput": 4, "slo": 3},
    requeue_budgets={"job": 2, "serve": 0},
))

_register(Corpus(
    id="mid-rollout",
    description="a 2-replica serve snapshotted right after a template "
                "change: the surge rollout must converge to the new "
                "generation with zero unready windows and go silent",
    start_round=2, rounds=10,
    seed_objects=_seed_mid_rollout,
    finalize=_fin_mid_rollout,
    write_budgets={"job": 0, "serve": 18, "autoscaler": 0, "drain": 0,
                   "rescheduler": 0, "goodput": 0, "slo": 0},
    requeue_budgets={"job": 0, "serve": 4},
))

_register(Corpus(
    id="spike",
    description="an autoscaled serve under an oscillating front door "
                "(900/100 qps flips for six rounds, then settles): one "
                "scale-up, one stabilized scale-down, no chasing",
    start_round=2, rounds=16,
    seed_objects=_seed_spike,
    stimulus=_spike_stimulus,
    write_budgets={"job": 0, "serve": 18, "autoscaler": 6, "drain": 0,
                   "rescheduler": 0, "goodput": 0, "slo": 0},
    requeue_budgets={"job": 0, "serve": 4},
))


# ---------------------------------------------------------------------------
# the co-simulation world
# ---------------------------------------------------------------------------


class World:
    """One deterministic closed-loop universe: backing store on a virtual
    clock, the six REAL loop instances (fresh, as after a leader
    failover), the gang scheduler + a hollow kubelet as the environment
    ('fleet'), and the SLO shell. The harness owns every tick."""

    def __init__(self, corpus: Corpus,
                 snapshot: Optional[Dict[str, Any]] = None):
        self.corpus = corpus
        self.backing = ObjectStore()
        self.now = EPOCH
        # deterministic virtual clock for every store-stamped timestamp
        self.backing._now = lambda: self.now
        self.store = RecordingStore(self.backing)
        if snapshot is not None:
            restore_store(self.backing, snapshot)
        self.jobctl = TPUJobController(
            self.store, EventRecorder(self.store), ControllerOptions())
        self.servectl = TPUServeController(self.store)
        self.autoscaler = ServeAutoscaler(self.store)
        self.drain = DrainController(self.store)
        self.rescheduler = Rescheduler(
            self.store, EventRecorder(self.store), **RESCHED_KW)
        self.goodput = GoodputAggregator(self.store)
        self.sched = GangScheduler(self.store)
        self.slo = _SLOShell(self.store)
        self.requeues: Dict[str, int] = {"job": 0, "serve": 0}
        # (step, round, hash) after every author action
        self.hashes: List[Tuple[int, int, str]] = []

    # -- participants -------------------------------------------------------

    def _tick_loop(self, name: str) -> None:
        if name == "job":
            for job in sorted(self.store.list("TPUJob"),
                              key=lambda j: j.metadata.key()):
                if not self.jobctl.sync_handler(job.metadata.key()):
                    self.requeues["job"] += 1
        elif name == "serve":
            for srv in sorted(self.store.list("TPUServe"),
                              key=lambda s: s.metadata.key()):
                if not self.servectl.sync_handler(srv.metadata.key()):
                    self.requeues["serve"] += 1
        elif name == "autoscaler":
            self.autoscaler.tick(now=self.now)
        elif name == "drain":
            self.drain.sync(now=self.now)
        elif name == "rescheduler":
            self.rescheduler.sync(now=self.now)
        elif name == "goodput":
            self.goodput.tick(now=self.now)
        else:  # pragma: no cover - defensive
            raise ConvergeError(f"unknown loop {name!r}")

    def _fleet_step(self) -> None:
        """The environment's move: the gang scheduler places, the hollow
        kubelet runs whatever got bound."""
        self.sched.sync()
        for p in self.store.list("Pod"):
            if p.is_finished() or not p.spec.node_name:
                continue
            if p.status.phase == PodPhase.PENDING:
                self.store.patch(
                    "Pod", p.metadata.namespace, p.metadata.name,
                    {"metadata": {"uid": p.metadata.uid},
                     "status": {"phase": PodPhase.RUNNING, "ready": True}},
                    subresource="status",
                )

    def _publish_stats(self, rnd: int) -> None:
        fn = self.corpus.pod_stats
        if fn is None:
            return
        for p in sorted(self.store.list("Pod"),
                        key=lambda p: p.metadata.key()):
            if p.is_finished() or p.status.phase != PodPhase.RUNNING:
                continue
            blob = fn(self, p, rnd)
            if blob is None:
                continue
            bounded = bounded_train_stats(**blob)
            if bounded == (p.status.train_stats or {}):
                continue  # a quiet workload publishes nothing new
            self.store.patch(
                "Pod", p.metadata.namespace, p.metadata.name,
                {"metadata": {"uid": p.metadata.uid},
                 "status": {"train_stats": bounded}},
                subresource="status",
            )

    def _hash_point(self) -> None:
        # hash first, THEN advance the step counter: writes made during the
        # upcoming tick must share the step of the hash point AFTER them,
        # or a pre-tick revisit would claim a post-hash write in its span
        self.hashes.append(
            (self.store.step, self.store.round, canonical_hash(self.backing)))
        self.store.step += 1

    # -- one round ----------------------------------------------------------

    def run_round(self, rnd: int, order: Sequence[int]) -> None:
        self.now = EPOCH + rnd * DT
        self.store.round = rnd
        self.store.author = "fleet"
        if self.corpus.stimulus is not None:
            self.corpus.stimulus(self, rnd)
        self._publish_stats(rnd)
        self._hash_point()
        for li in order:
            name = LOOPS[li]
            self.store.author = name
            self._tick_loop(name)
            self._hash_point()
            self.store.author = "fleet"
            self._fleet_step()
            self._hash_point()
        self.store.author = "slo"
        self.slo.tick(
            self.corpus.burns(rnd) if self.corpus.burns else None, self.now)
        self._hash_point()
        self.store.author = "fleet"
        self._fleet_step()
        self._hash_point()


# ---------------------------------------------------------------------------
# orders + replay tokens
# ---------------------------------------------------------------------------


def enumerate_orders(seed: int) -> List[str]:
    """identity + reversed + four seeded shuffles, deduplicated."""
    orders = [_IDENTITY, _IDENTITY[::-1]]
    rng = random.Random(seed)
    digits = list(_IDENTITY)
    while len(orders) < 6:
        rng.shuffle(digits)
        cand = "".join(digits)
        if cand not in orders:
            orders.append(cand)
    return orders


def format_token(corpus_id: str, seed: int, order: str) -> str:
    # fail closed at mint time: a token with a non-int seed would only
    # surface later, when someone tries to --replay the printed line
    if not isinstance(seed, int) or isinstance(seed, bool):
        raise TokenError(f"seed must be an int, got {seed!r}")
    return f"v1:conv:{corpus_id}:{seed}:{order}"


def parse_token(token: str) -> Tuple[str, int, str]:
    parts = token.split(":")
    if len(parts) != 5 or parts[0] != "v1" or parts[1] != "conv":
        raise TokenError(
            f"bad replay token {token!r}: want v1:conv:<corpus>:<seed>:"
            f"<order>")
    _, _, corpus_id, seed_s, order = parts
    if corpus_id not in CORPORA:
        raise TokenError(
            f"bad replay token {token!r}: unknown corpus {corpus_id!r} "
            f"(have: {', '.join(sorted(CORPORA))})")
    try:
        seed = int(seed_s)
    except ValueError:
        raise TokenError(
            f"bad replay token {token!r}: seed {seed_s!r} is not an int")
    if sorted(order) != sorted(_IDENTITY):
        raise TokenError(
            f"bad replay token {token!r}: order {order!r} is not a "
            f"permutation of {_IDENTITY}")
    return corpus_id, seed, order


# ---------------------------------------------------------------------------
# judges
# ---------------------------------------------------------------------------


@dataclass
class RunResult:
    corpus_id: str
    seed: int
    order: str
    mutant: Optional[str]
    rounds: Tuple[int, int]               # [first, last] judged rounds
    writes: Dict[str, int]
    requeues: Dict[str, int]
    violations: List[str]

    @property
    def token(self) -> str:
        return format_token(self.corpus_id, self.seed, self.order)

    @property
    def ok(self) -> bool:
        return not self.violations


# controller authors whose writes can constitute an oscillation. The
# fleet (scripted stimulus + kubelet shims) is the environment's churn,
# not a loop fighting itself — but the SLO shell drives the real alert
# state machine, so its writes count.
_CYCLE_AUTHORS = frozenset(LOOPS) | {"slo"}


def _judge(world: World, result: RunResult) -> None:
    corpus = world.corpus
    first, last = result.rounds
    writes = world.store.writes

    # quiescence: the stimulus scripts all freeze before the tail, so the
    # final two rounds must be write-free from EVERY author
    tail = [w for w in writes if w.round >= last - 1]
    if tail:
        by = sorted({f"{w.author}:{w.verb} {w.kind} {w.key}" for w in tail})
        result.violations.append(
            f"quiescence: {len(tail)} write(s) in the final two rounds "
            f"(rounds {last - 1}-{last}) — the plane never settles: "
            + "; ".join(by[:6]) + ("; ..." if len(by) > 6 else ""))

    # write cycles: a canonical hash revisiting an earlier value with a
    # DIFFERENT state in between and >= 1 loop-authored non-Event write in
    # the span is an oscillation (fleet stimulus and audit Events are the
    # environment's churn, not a loop fighting itself)
    seen: Dict[str, int] = {}
    cycle = None
    for idx, (step, rnd, h) in enumerate(world.hashes):
        if h in seen:
            i = seen[h]
            stretch = world.hashes[i + 1: idx]
            if any(hh != h for _, _, hh in stretch):
                lo_step = world.hashes[i][0]
                span = [w for w in writes
                        if lo_step < w.step <= step
                        and w.author in _CYCLE_AUTHORS and w.kind != "Event"]
                if span:
                    cycle = (world.hashes[i][1], rnd, span)
                    break
        else:
            seen[h] = idx
    if cycle is not None:
        lo_rnd, hi_rnd, span = cycle
        trail = ", ".join(
            f"{w.author}:{w.verb} {w.kind} {w.key}" for w in span[:8])
        result.violations.append(
            f"cycle: state hash at round {hi_rnd} revisits round {lo_rnd} "
            f"after {len(span)} loop write(s) — an oscillation: {trail}"
            + (", ..." if len(span) > 8 else ""))

    # bounded wasted work: writes per author, requeues per controller
    for author in sorted(corpus.write_budgets):
        budget = corpus.write_budgets[author]
        got = result.writes.get(author, 0)
        if got > budget:
            result.violations.append(
                f"budget: author '{author}' made {got} store writes "
                f"(budget {budget}) over rounds {first}-{last}")
    for loop in sorted(corpus.requeue_budgets):
        budget = corpus.requeue_budgets[loop]
        got = result.requeues.get(loop, 0)
        if got > budget:
            result.violations.append(
                f"budget: loop '{loop}' requeued {got} times "
                f"(budget {budget})")


# ---------------------------------------------------------------------------
# corpus snapshots + runs
# ---------------------------------------------------------------------------

_SNAPSHOT_CACHE: Dict[str, Dict[str, Any]] = {}


def get_corpus(corpus_id: str) -> Corpus:
    try:
        return CORPORA[corpus_id]
    except KeyError:
        raise CorpusError(
            f"unknown corpus {corpus_id!r} (have: "
            f"{', '.join(sorted(CORPORA))})")


def corpus_snapshot(corpus_id: str) -> Dict[str, Any]:
    """Build (and cache) the corpus start state by driving the REAL loops
    through the scripted warmup — every snapshot is reachable by
    construction, not hand-assembled."""
    if corpus_id in _SNAPSHOT_CACHE:
        return _SNAPSHOT_CACHE[corpus_id]
    corpus = get_corpus(corpus_id)
    world = World(corpus)
    world.store.author = "setup"
    corpus.seed_objects(world)
    identity = tuple(range(len(LOOPS)))
    for rnd in range(corpus.start_round):
        world.run_round(rnd, identity)
    if corpus.finalize is not None:
        world.store.author = "setup"
        corpus.finalize(world)
    doc = snapshot_store(world.backing)
    _SNAPSHOT_CACHE[corpus_id] = doc
    return doc


def load_snapshot_file(path: str) -> Dict[str, Any]:
    """Fail-closed external snapshot loading (the --snapshot seam)."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise CorpusError(f"cannot read snapshot {path!r}: {e}")
    except json.JSONDecodeError as e:
        raise CorpusError(f"malformed snapshot JSON in {path!r}: {e}")
    # validate by restoring into a scratch store before anyone trusts it
    try:
        restore_store(ObjectStore(), doc)
    except ScenarioError as e:
        raise CorpusError(f"invalid snapshot {path!r}: {e}")
    return doc


def run_one(corpus_id: str, seed: int, order: str,
            mutant: Optional[str] = None,
            rounds: Optional[int] = None,
            snapshot: Optional[Dict[str, Any]] = None) -> RunResult:
    corpus = get_corpus(corpus_id)
    if sorted(order) != sorted(_IDENTITY):
        raise TokenError(f"order {order!r} is not a permutation of "
                         f"{_IDENTITY}")
    doc = snapshot if snapshot is not None else corpus_snapshot(corpus_id)
    world = World(corpus, snapshot=doc)
    n_rounds = corpus.rounds if rounds is None else rounds
    first = corpus.start_round
    last = first + n_rounds - 1
    undo = None
    if mutant is not None:
        undo = get_mutant(mutant).apply(world)
    try:
        for rnd in range(first, last + 1):
            world.run_round(rnd, tuple(int(c) for c in order))
    finally:
        if undo is not None:
            undo()
    result = RunResult(
        corpus_id=corpus_id, seed=seed, order=order, mutant=mutant,
        rounds=(first, last), writes=world.store.counts(),
        requeues=dict(world.requeues), violations=[],
    )
    _judge(world, result)
    return result


def run_corpus(corpus_id: str, seed: int = 0,
               mutant: Optional[str] = None,
               rounds: Optional[int] = None,
               orders: Optional[Sequence[str]] = None) -> List[RunResult]:
    outs = []
    for order in (orders if orders is not None else enumerate_orders(seed)):
        outs.append(run_one(corpus_id, seed, order, mutant=mutant,
                            rounds=rounds))
    return outs


# ---------------------------------------------------------------------------
# seeded mutants — the checker's own bar
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Mutant:
    """One reintroduced defect class. ``apply`` arms it on a fresh World
    and returns an undo closure (mutants that monkeypatch module/class
    seams MUST restore them — the harness runs real loops right after)."""

    id: str
    corpus_id: str            # the corpus whose script exposes it
    description: str
    apply: Callable[[World], Callable[[], None]]


def _m1_apply(world: World) -> Callable[[], None]:
    prev = world.rescheduler.hysteresis_s
    world.rescheduler.hysteresis_s = 0.0

    def undo() -> None:
        world.rescheduler.hysteresis_s = prev
    return undo


def _m2_apply(world: World) -> Callable[[], None]:
    orig = autoscaler_mod.recommend

    def myopic(samples, current, targets, now, last_scale_up_t=None):
        return orig(samples[-1:], current, targets, now,
                    last_scale_up_t=last_scale_up_t)

    autoscaler_mod.recommend = myopic

    def undo() -> None:
        autoscaler_mod.recommend = orig
    return undo


def _m3_apply(world: World) -> Callable[[], None]:
    def always_write(job) -> bool:
        world.store.patch(
            "TPUJob", job.metadata.namespace, job.metadata.name,
            {"metadata": {"uid": job.metadata.uid},
             "status": job.status.to_dict()},
            subresource="status",
        )
        return True

    world.jobctl._write_status = always_write
    return lambda: None  # instance-local; dies with the World


def _m4_apply(world: World) -> Callable[[], None]:
    orig = GangScheduler.__dict__["_pick_node"]

    def flat_least_loaded(nodes, used, cost):
        best = best_load = None
        for n in nodes:
            cap = n.status.capacity_chips
            u = used.get(n.metadata.name, 0)
            if cap is not None and u + cost > cap:
                continue
            if best is None or u < best_load:
                best, best_load = n.metadata.name, u
        return best

    GangScheduler._pick_node = staticmethod(flat_least_loaded)

    def undo() -> None:
        GangScheduler._pick_node = orig
    return undo


def _m5_apply(world: World) -> Callable[[], None]:
    world.slo.policy = replace(world.slo.policy, clear_hold_s=0.0)
    return lambda: None


def _m6_apply(world: World) -> Callable[[], None]:
    orig = world.jobctl.sync_handler

    def hot_loop(key: str) -> bool:
        orig(key)
        return False  # "retry forever": the classic busy reconcile

    world.jobctl.sync_handler = hot_loop
    return lambda: None


MUTANTS: Dict[str, Mutant] = {m.id: m for m in (
    Mutant("m1-no-hysteresis", "straggler",
           "rescheduler hysteresis removed: a gang whose straggler "
           "survives the move is migrated again on every re-blame "
           "(ping-pong)", _m1_apply),
    Mutant("m2-no-stabilization", "spike",
           "autoscaler stabilization window removed (decides on the "
           "newest sample only): scale flaps with every qps flip",
           _m2_apply),
    Mutant("m3-no-elision", "fragmented",
           "job status no-op elision removed (unconditional status write "
           "per reconcile): the plane never quiesces", _m3_apply),
    Mutant("m4-no-anti-hop", "straggler",
           "scheduler placement tiers removed (flat least-loaded): a "
           "migrated gang lands right back on the flagged sick node",
           _m4_apply),
    Mutant("m5-no-clear-hold", "quota",
           "SLO clear-hold hysteresis removed: the alert re-pages on "
           "every flap across the fire threshold", _m5_apply),
    Mutant("m6-requeue-always", "fragmented",
           "job reconcile returns 'retry' unconditionally: a hot loop "
           "that burns the queue forever", _m6_apply),
)}


def get_mutant(mutant_id: str) -> Mutant:
    try:
        return MUTANTS[mutant_id]
    except KeyError:
        raise ConvergeError(
            f"unknown mutant {mutant_id!r} (have: "
            f"{', '.join(sorted(MUTANTS))})")


# ---------------------------------------------------------------------------
# replay + self-test
# ---------------------------------------------------------------------------


def replay(token: str, mutant: Optional[str] = None,
           expect_corpus: Optional[str] = None,
           expect_seed: Optional[int] = None) -> RunResult:
    """Re-execute the exact run a token encodes. Explicitly-passed
    --corpus/--seed must MATCH the token: silently preferring one over
    the other would replay a different run than the user asked for."""
    corpus_id, seed, order = parse_token(token)
    if expect_corpus is not None and expect_corpus != corpus_id:
        raise TokenError(
            f"replay token names corpus {corpus_id!r} but --corpus "
            f"{expect_corpus!r} was passed: refusing to guess")
    if expect_seed is not None and expect_seed != seed:
        raise TokenError(
            f"replay token encodes seed {seed} but --seed {expect_seed} "
            f"was passed: refusing to guess")
    return run_one(corpus_id, seed, order, mutant=mutant)


def self_test(seed: int = 0, verbose: bool = False,
              log: Optional[Callable[[str], None]] = None) -> List[str]:
    """The checker's own gate: every REAL loop runs the whole corpus
    clean under every enumerated order, and every seeded mutant is caught
    on its corpus — with a replay token that reproduces identically."""
    say = log or (lambda s: None)
    failures: List[str] = []
    orders = enumerate_orders(seed)

    for corpus_id in sorted(CORPORA):
        for order in orders:
            res = run_one(corpus_id, seed, order)
            if res.ok:
                say(f"  real  {corpus_id:<12} order={order}: converged")
            else:
                say(f"  real  {corpus_id:<12} order={order}: "
                    f"{len(res.violations)} violation(s)")
                failures.append(
                    f"real loops violated convergence on corpus "
                    f"'{corpus_id}' order {order} "
                    f"(replay: {res.token}): {res.violations[0]}")

    for mid in sorted(MUTANTS):
        mutant = MUTANTS[mid]
        caught: Optional[RunResult] = None
        for order in orders:
            res = run_one(mutant.corpus_id, seed, order, mutant=mid)
            if not res.ok:
                caught = res
                break
        if caught is None:
            failures.append(
                f"mutant '{mid}' NOT caught on corpus "
                f"'{mutant.corpus_id}' under any of {len(orders)} orders")
            say(f"  mut   {mid:<20} ESCAPED")
            continue
        # the token must reproduce the identical verdict (determinism)
        again = run_one(caught.corpus_id, seed, caught.order, mutant=mid)
        if again.violations != caught.violations:
            failures.append(
                f"mutant '{mid}' verdict is not deterministic: replay of "
                f"{caught.token} produced different violations")
        say(f"  mut   {mid:<20} caught (replay: {caught.token} "
            f"--mutant {mid})")
    return failures


def render_result(res: RunResult) -> str:
    writes = ", ".join(
        f"{a}={res.writes.get(a, 0)}" for a in (*LOOPS, "slo", "fleet"))
    lines = [
        f"corpus {res.corpus_id} order={res.order} "
        f"rounds={res.rounds[0]}..{res.rounds[1]}"
        + (f" mutant={res.mutant}" if res.mutant else ""),
        f"  writes: {writes}",
        f"  requeues: job={res.requeues.get('job', 0)} "
        f"serve={res.requeues.get('serve', 0)}",
    ]
    if res.ok:
        lines.append("  CONVERGED")
    else:
        for v in res.violations:
            lines.append(f"  VIOLATION {v}")
        lines.append(f"  replay: {res.token}")
    return "\n".join(lines)
