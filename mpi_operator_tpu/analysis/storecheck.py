"""storecheck: model-differential fuzzing of the store seam.

opcheck (PR 5) checks the store against its sequential spec — but only on
histories the existing suites happen to produce. This module generates the
histories: a **seeded generator** draws op sequences over the five store
verbs + the status subresource + ``patch_batch`` + watch-ring resumes
(valid/invalid rv and uid preconditions, label-selected lists,
ring-boundary resume anchors, interleaved deletes/recreates) and executes
each sequence identically against all three backends —

- ``ObjectStore`` (in-memory),
- ``SqliteStore`` (the durable file backend),
- ``HttpStoreClient`` → ``StoreServer`` (the wire seam, small event ring),
- the 3-node replica set through its failover client (leader writes,
  follower reads and watch — machinery/replicated_store.py, ISSUE 8),

diffing **return values, error classes, final state and delivered watch
streams** op-by-op against :class:`analysis.model.ModelStore`, the
executable sequential reference (which itself cross-checks every result
through ``StoreModel.apply``, so the fuzzer's oracle and the
linearizability checker's oracle can never fork).

Ops are **symbolic** (``{"rv": "stale"}``, ``{"anchor": "dropped-1"}``) and
resolved against the model's state at execution time, so ANY subsequence
of a generated sequence is itself executable — that is what makes
delta-debug shrinking sound. A divergence is ddmin-shrunk to a minimal op
subsequence and printed as a deterministic replay token::

    v1:fuzz:<seed>:<op-indices>

in the explore.py style: ``--replay`` re-executes the exact subsequence
(twice-identical is asserted by the selftest), and every seeded mutant's
minimal repro is pinned as JSON under ``tests/data/storecheck/``.

The detector's own acceptance gate (:func:`self_test`): each seeded
**mutant backend** — delete without an rv bump, patch that drops the uid
pin, update that ignores the rv precondition, a status subresource that
leaks spec writes, an event ring that replays one event past
``_dropped_rv``, a batch that aborts at the first error — MUST be caught
within the default budget, shrunk, and replay twice-identical; the three
real backends MUST fuzz clean at the same budget. This is the standing
acceptance harness ROADMAP item 1's replicated store will be run against:
a replica set plugs into the same duck-typed surface and must diff clean
against the same model.
"""

from __future__ import annotations

import copy
import json
import os
import queue
import random
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from mpi_operator_tpu.analysis import allowlist
from mpi_operator_tpu.analysis.model import TERMINAL_PHASES, ModelStore
from mpi_operator_tpu.machinery.serialize import decode, encode
from mpi_operator_tpu.machinery.store import (
    AlreadyExists,
    BadPatch,
    Conflict,
    NotFound,
)

TOKEN_VERSION = "v1"

# the store error classes a differential outcome may name; anything else
# escaping a backend is a harness failure, not a diff
_STORE_ERRORS = (NotFound, AlreadyExists, Conflict, BadPatch)

# fuzz-harness ring capacity: small enough that a default-budget sequence
# trims it (ring-boundary resume anchors become meaningful), large enough
# that the lock-step watch drain keeps the client cursor inside it
RING_CAPACITY = 8

_KINDS = ("Pod", "TPUJob", "Node")
_NS = {"Pod": "default", "TPUJob": "default", "Node": "nodes"}
_NAMES = ("a", "b", "c")
_PHASES = ("Pending", "Running", "Succeeded", "Failed")
_ANCHORS = ("dropped", "dropped-1", "dropped+1", "mid", "newest", "future")


class FuzzError(RuntimeError):
    """The fuzz machinery itself failed (bad token, harness bug) —
    distinct from a Divergence, which is a finding."""


@dataclass(frozen=True)
class FuzzBudget:
    """``sequences`` seeds derived from the base seed, ``ops`` symbolic
    ops per sequence."""

    sequences: int = 8
    ops: int = 48


FAST_BUDGET = FuzzBudget(sequences=3, ops=40)
DEFAULT_BUDGET = FuzzBudget()
EXHAUSTIVE_BUDGET = FuzzBudget(sequences=40, ops=96)


# ---------------------------------------------------------------------------
# generation (pure function of the seed: a stream of symbolic ops)
# ---------------------------------------------------------------------------


def generate(seed: int, n_ops: int) -> List[Dict[str, Any]]:
    """The first ``n_ops`` symbolic ops of seed ``seed``'s stream. Draws
    happen strictly per-op, so ``generate(seed, k)`` is a prefix of
    ``generate(seed, n)`` for k <= n — replay tokens only need the seed
    and the highest index."""
    rng = random.Random(seed)
    uid_seq = 0
    ops: List[Dict[str, Any]] = []
    for _ in range(n_ops):
        kind = rng.choices(_KINDS, weights=(6, 2, 2))[0]
        name = rng.choice(_NAMES)
        verb = rng.choices(
            ("create", "patch", "update", "delete", "get", "list",
             "patch_batch", "watch_resume"),
            weights=(18, 24, 10, 10, 8, 8, 12, 10),
        )[0]
        if verb == "create":
            uid_seq += 1
            ops.append({
                "op": "create", "kind": kind, "name": name,
                "uid": f"u{seed}-{uid_seq}",
                "labels": {"job": rng.choice(("j1", "j2"))},
            })
        elif verb == "patch":
            ops.append(_gen_patch(rng, kind, name))
        elif verb == "update":
            ops.append({
                "op": "update", "kind": kind, "name": name,
                "rv": rng.choices(("current", "stale", "future"),
                                  weights=(6, 3, 1))[0],
                "force": rng.random() < 0.15,
                "label": ["bump", str(rng.randrange(10))],
            })
        elif verb == "delete":
            ops.append({"op": "delete", "kind": kind, "name": name})
        elif verb == "get":
            ops.append({"op": "get", "kind": kind, "name": name})
        elif verb == "list":
            ops.append({
                "op": "list", "kind": kind,
                "namespace": rng.choice((None, _NS[kind])),
                "selector": rng.choice(
                    (None, {"job": "j1"}, {"job": "j2"})
                ),
            })
        elif verb == "patch_batch":
            items = [
                _gen_patch(rng, rng.choices(_KINDS, weights=(6, 2, 2))[0],
                           rng.choice(_NAMES))
                for _ in range(rng.randrange(2, 5))
            ]
            ops.append({"op": "patch_batch", "items": items})
        else:  # watch_resume (ring-boundary anchors; http backend only)
            ops.append({
                "op": "watch_resume", "anchor": rng.choice(_ANCHORS),
            })
    return ops


def _gen_patch(rng: random.Random, kind: str, name: str) -> Dict[str, Any]:
    sub = rng.random() < 0.55
    shape = rng.choices(
        ("status", "labels", "bad-spec-via-status", "bad-identity",
         "bad-non-dict"),
        weights=(10, 6, 2, 1, 1),
    )[0]
    if shape == "status":
        changes: Dict[str, Any] = rng.choice((
            {"phase": rng.choice(_PHASES)},
            {"reason": rng.choice(("", "Evicted", "x"))},
            {"message": f"m{rng.randrange(5)}"},
            {"ready": rng.random() < 0.5},
        ))
        body: Dict[str, Any] = {"status": changes}
        sub = True if "phase" in changes else sub
    elif shape == "labels":
        body = {"metadata": {"labels": {
            rng.choice(("job", "extra")): rng.choice(("j1", "j2", None)),
        }}}
        sub = False
    elif shape == "bad-spec-via-status":
        body = {"spec": {"node_name": "stolen"}}
        sub = True  # → BadPatch: the subresource freezes spec
    elif shape == "bad-identity":
        body = {"metadata": {"name": "forged"}}
        sub = False  # → BadPatch: identity freeze
    else:
        body = "not-a-dict"  # type: ignore[assignment]
        sub = False  # → BadPatch: malformed patch
    return {
        "op": "patch", "kind": kind, "name": name,
        "rv": rng.choices((None, "current", "stale"), weights=(5, 3, 2))[0],
        "uid": rng.choices((None, "current", "wrong"), weights=(5, 3, 2))[0],
        "subresource": "status" if sub else None,
        "body": body,
    }


# ---------------------------------------------------------------------------
# resolution (symbolic → concrete, against the model's current state)
# ---------------------------------------------------------------------------


def _resolve_rv(choice, cur_rv: int) -> Optional[int]:
    if choice is None:
        return None
    if choice == "current":
        return cur_rv
    if choice == "stale":
        return max(cur_rv - 1, 0)
    return cur_rv + 100  # "future"


def _resolve_patch(op: Dict[str, Any], model: ModelStore) -> Dict[str, Any]:
    kind, name = op["kind"], op["name"]
    ns = _NS[kind]
    key = (kind, ns, name)
    cur = model.snapshot().get(key)
    cur_meta = (cur or {}).get("metadata", {})
    cur_rv = cur_meta.get("resource_version", 0)
    body = copy.deepcopy(op["body"])
    if isinstance(body, dict):
        status = body.get("status")
        if (
            kind == "Pod"
            and op.get("subresource") == "status"
            and isinstance(status, dict)
            and "phase" in status
        ):
            # terminal write-once clamp: the SYSTEM spec (StoreModel /
            # patch_pod_status) forbids resurrecting a terminal Pod phase;
            # real clients never emit that op, so neither does the fuzzer.
            # Clamping at resolution (not generation) keeps every
            # subsequence executable.
            cur_phase = ((cur or {}).get("status") or {}).get("phase")
            if cur_phase in TERMINAL_PHASES:
                status["phase"] = cur_phase
        meta: Dict[str, Any] = {}
        rv = _resolve_rv(op.get("rv"), cur_rv)
        if rv is not None and rv > 0:
            meta["resource_version"] = rv
        if op.get("uid") == "current" and cur_meta.get("uid"):
            meta["uid"] = cur_meta["uid"]
        elif op.get("uid") == "wrong":
            meta["uid"] = "u-bogus"
        if meta:
            body = dict(body, metadata={**meta, **body.get("metadata", {})})
    return {
        "op": "patch", "kind": kind, "ns": ns, "name": name,
        "patch": body, "subresource": op.get("subresource"),
    }


def resolve(op: Dict[str, Any], model: ModelStore,
            capacity: int = RING_CAPACITY) -> Dict[str, Any]:
    """Resolve one symbolic op against the model state into the concrete
    call every backend will receive — identical for all of them, because
    resolution only ever consults the MODEL (a backend that drifted from
    the model diverges at the comparison, not at resolution)."""
    verb = op["op"]
    kind = op.get("kind", "Pod")
    ns = _NS.get(kind, "default")
    if verb == "create":
        return {
            "op": "create", "kind": kind,
            "obj": {
                "kind": kind,
                "metadata": {
                    "name": op["name"], "namespace": ns, "uid": op["uid"],
                    "labels": dict(op.get("labels") or {}),
                    # pre-stamped so no backend falls back to time.time()
                    "creation_timestamp": 1000.0,
                },
            },
        }
    if verb == "get":
        return {"op": "get", "kind": kind, "ns": ns, "name": op["name"]}
    if verb == "delete":
        return {"op": "delete", "kind": kind, "ns": ns, "name": op["name"]}
    if verb == "list":
        return {
            "op": "list", "kind": kind, "namespace": op.get("namespace"),
            "selector": op.get("selector"),
        }
    if verb == "update":
        key = (kind, ns, op["name"])
        cur = model.snapshot().get(key)
        if cur is None:
            obj = {
                "kind": kind,
                "metadata": {"name": op["name"], "namespace": ns,
                             "uid": "u-ghost", "resource_version": 1,
                             "creation_timestamp": 1000.0},
            }
        else:
            obj = copy.deepcopy(cur)
            labels = obj.setdefault("metadata", {}).setdefault("labels", {})
            labels[op["label"][0]] = op["label"][1]
            obj["metadata"]["resource_version"] = _resolve_rv(
                op["rv"], obj["metadata"].get("resource_version", 0)
            )
        return {"op": "update", "kind": kind, "obj": obj,
                "force": bool(op.get("force"))}
    if verb == "patch":
        return _resolve_patch(op, model)
    if verb == "patch_batch":
        # items resolve against the state as the PREFIX of the batch leaves
        # it (the applied-prefix contract), via a scratch model clone
        scratch = copy.deepcopy(model)
        items = []
        for item in op["items"]:
            c = _resolve_patch(item, scratch)
            items.append({
                "kind": c["kind"], "namespace": c["ns"], "name": c["name"],
                "patch": c["patch"], "subresource": c["subresource"],
            })
            try:
                scratch.patch(c["kind"], c["ns"], c["name"], c["patch"],
                              subresource=c["subresource"])
            except _STORE_ERRORS:
                pass
        return {"op": "patch_batch", "items": items}
    if verb == "watch_resume":
        dropped = model.ring_dropped_rv(capacity)
        newest = model.current_rv()
        anchor = {
            "dropped": dropped,
            "dropped-1": max(dropped - 1, 0),
            "dropped+1": min(dropped + 1, newest),
            "mid": (dropped + newest) // 2,
            "newest": newest,
            "future": newest + 50,
        }[op["anchor"]]
        return {
            "op": "watch_resume", "anchor": anchor, "capacity": capacity,
            # ring catch-up target: every model event must be in the
            # server log before the resume is meaningful
            "expected_head": len(model.events),
        }
    raise FuzzError(f"unknown symbolic op {verb!r}")


# ---------------------------------------------------------------------------
# execution + outcome normalization
# ---------------------------------------------------------------------------


def _norm_exc(e: Exception) -> Dict[str, Any]:
    return {"error": type(e).__name__}


def _exec_model(model: ModelStore, c: Dict[str, Any]) -> Dict[str, Any]:
    verb = c["op"]
    try:
        if verb == "create":
            return {"ok": model.create(c["kind"], c["obj"])}
        if verb == "get":
            return {"ok": model.get(c["kind"], c["ns"], c["name"])}
        if verb == "update":
            return {"ok": model.update(c["kind"], c["obj"], c["force"])}
        if verb == "patch":
            return {"ok": model.patch(c["kind"], c["ns"], c["name"],
                                      c["patch"],
                                      subresource=c["subresource"])}
        if verb == "delete":
            return {"ok": model.delete(c["kind"], c["ns"], c["name"])}
        if verb == "list":
            return {"list": model.list(c["kind"], c["namespace"],
                                       c["selector"])}
        if verb == "patch_batch":
            return {"batch": [
                _norm_exc(r) if isinstance(r, Exception) else {"ok": r}
                for r in model.patch_batch(c["items"])
            ]}
        if verb == "watch_resume":
            tail = model.resume_after_rv(c["anchor"], c["capacity"])
            if tail is None:
                return {"relist": _relist_view(model.snapshot().values())}
            return {"resume": [list(t) for t in tail]}
    except _STORE_ERRORS as e:
        return _norm_exc(e)
    raise FuzzError(f"unknown concrete op {verb!r}")


def _relist_view(objs) -> List[List[Any]]:
    out = []
    for o in objs:
        m = o.get("metadata") or {}
        out.append([o.get("kind"), m.get("namespace"), m.get("name"),
                    m.get("resource_version")])
    return sorted(out)


@dataclass
class Harness:
    """One backend under test: the duck-typed store client, its watch
    queue, and (HTTP only) the server whose event ring serves resumes.
    The watch is LAZY (``start_watch``): shrink probes that only diff op
    results skip it, which keeps ddmin from paying a watch-poller
    bootstrap + teardown per probe."""

    name: str
    store: Any
    server: Any = None
    teardown: Callable[[], None] = lambda: None
    watch_fn: Optional[Callable[[], Any]] = None
    watch_q: Any = None
    delivered: List[Tuple[str, str, str, str, int]] = field(
        default_factory=list
    )

    def start_watch(self) -> None:
        """Register the watch — must run BEFORE the first op so the
        delivered stream covers every event."""
        if self.watch_q is None and self.watch_fn is not None:
            self.watch_q = self.watch_fn()

    def drain_watch(self, expected: int, timeout: float = 5.0) -> None:
        """Lock-step drain: pull delivered events until ``expected`` have
        arrived (or the deadline passes — the comparison then surfaces the
        shortfall). Keeping the client caught up after every op also keeps
        its cursor inside the small fuzz ring, so the delivered stream
        never legally relists mid-sequence."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while len(self.delivered) < expected:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return
            try:
                ev = self.watch_q.get(timeout=min(remaining, 0.25))
            except queue.Empty:
                continue
            m = ev.obj.metadata
            self.delivered.append(
                (ev.type, ev.kind, m.namespace, m.name, m.resource_version)
            )


def _exec_backend(h: Harness, c: Dict[str, Any]) -> Dict[str, Any]:
    verb = c["op"]
    store = h.store
    try:
        if verb == "create":
            return {"ok": encode(store.create(decode(c["kind"], c["obj"])))}
        if verb == "get":
            return {"ok": encode(store.get(c["kind"], c["ns"], c["name"]))}
        if verb == "update":
            # oplint: disable=RMW001 — the differential harness MUST
            # drive the raw verbs (stale/forced updates included): the
            # rv-precondition behavior under test IS the get+update race
            # the rule bans in control-plane code
            return {"ok": encode(store.update(decode(c["kind"], c["obj"]),
                                              c["force"]))}
        if verb == "patch":
            return {"ok": encode(store.patch(
                c["kind"], c["ns"], c["name"], c["patch"],
                subresource=c["subresource"],
            ))}
        if verb == "delete":
            return {"ok": encode(store.delete(c["kind"], c["ns"],
                                              c["name"]))}
        if verb == "list":
            return {"list": [encode(o) for o in store.list(
                c["kind"], c["namespace"], c["selector"])]}
        if verb == "patch_batch":
            return {"batch": [
                _norm_exc(r) if isinstance(r, Exception)
                else {"ok": encode(r)}
                for r in store.patch_batch(c["items"])
            ]}
        if verb == "watch_resume":
            if h.server is None:
                return {"skipped": True}
            return _exec_resume(h, c)
    except _STORE_ERRORS as e:
        return _norm_exc(e)
    raise FuzzError(f"unknown concrete op {verb!r}")


def probe_resume(url: str, anchor: int, *, wait: float = 0.05,
                 timeout: float = 10.0) -> Dict[str, Any]:
    """One raw rv-anchored watch (re)registration against a store server
    — the ``?resource_version=`` wire probe, shared by the fuzzer, the
    crash-point explorer and the boundary tests so the query contract
    lives in ONE place. Returns the parsed payload: ``{"events": [...]}``
    (a provably-complete tail) or ``{"relist": [...]}`` (410 Gone)."""
    with urllib.request.urlopen(
        f"{url}/v1/watch?after=-1&resource_version={anchor}"
        f"&timeout={wait}",
        timeout=timeout,
    ) as r:
        return json.loads(r.read())


def _exec_resume(h: Harness, c: Dict[str, Any]) -> Dict[str, Any]:
    """An rv-anchored (re)registration against the server's event ring —
    the ?resource_version= contract: a provably-complete tail, or a
    relist (410 Gone)."""
    import time as _time

    deadline = _time.monotonic() + 5.0
    while h.server._log.head < c["expected_head"]:
        if _time.monotonic() > deadline:
            raise FuzzError("server event ring never caught up")
        _time.sleep(0.002)
    payload = probe_resume(h.server.url, c["anchor"])
    if "relist" in payload:
        return {"relist": _relist_view(payload["relist"])}
    return {"resume": [
        [e["type"], e["kind"],
         (e["object"].get("metadata") or {}).get("namespace"),
         (e["object"].get("metadata") or {}).get("name"), e["rv"]]
        for e in payload["events"]
    ]}


# ---------------------------------------------------------------------------
# backend factories (real + seeded mutants)
# ---------------------------------------------------------------------------


def _mk_memory() -> Harness:
    from mpi_operator_tpu.machinery.store import ObjectStore

    s = ObjectStore()
    return Harness("memory", s, watch_fn=lambda: s.watch(None))


def _mk_sqlite() -> Harness:
    import os
    import tempfile

    from mpi_operator_tpu.machinery.sqlite_store import SqliteStore

    d = tempfile.mkdtemp(prefix="storecheck-")
    s = SqliteStore(os.path.join(d, "fuzz.db"), poll_interval=0.01)

    def teardown():
        import shutil

        s.close()
        shutil.rmtree(d, ignore_errors=True)

    return Harness("sqlite", s, teardown=teardown,
                   watch_fn=lambda: s.watch(None))


def _mk_http() -> Harness:
    from mpi_operator_tpu.machinery.http_store import (
        HttpStoreClient,
        StoreServer,
    )
    from mpi_operator_tpu.machinery.store import ObjectStore

    srv = StoreServer(ObjectStore(), "127.0.0.1", 0,
                      log_capacity=RING_CAPACITY).start()
    client = HttpStoreClient(srv.url, watch_poll_timeout=0.5)

    def teardown():
        client.close()
        srv.stop()

    return Harness("http", client, server=srv, teardown=teardown,
                   watch_fn=lambda: client.watch(None))


def _mk_replica_parts():
    """A fresh manual-mode 3-node replica set: n0 elected leader, the
    failover client reading (and watching) from follower n1 — the
    replica set's OWN read contract is what the differential diff then
    exercises: every acked write must be visible on a follower the
    moment the ack returns (ship-to-all-reachable before ack)."""
    import shutil
    import tempfile

    from mpi_operator_tpu.machinery.replicated_store import ReplicaSet

    d = tempfile.mkdtemp(prefix="storecheck-replica-")
    rset = ReplicaSet(3, dir=d, poll_interval=0.01)
    if not rset.elect("n0"):
        raise FuzzError("fresh replica set failed its first election")
    client = rset.client(read_from="n1")

    def teardown():
        rset.stop()
        shutil.rmtree(d, ignore_errors=True)

    return rset, client, teardown


def _mk_replica() -> Harness:
    rset, client, teardown = _mk_replica_parts()
    return Harness("replica", client, teardown=teardown,
                   watch_fn=lambda: client.watch(None))


REAL_BACKENDS: Dict[str, Callable[[], Harness]] = {
    "memory": _mk_memory,
    "sqlite": _mk_sqlite,
    "http": _mk_http,
    "replica": _mk_replica,
}


def _mk_mutant_delete_no_rv_bump() -> Harness:
    """Seeded bug: delete removes the object but reuses its LAST rv on
    the DELETED event instead of consuming a fresh one — the exact
    skippable-deletion bug the rv-bump-on-delete contract (PR 1) exists
    to prevent."""
    from mpi_operator_tpu.machinery.store import DELETED, ObjectStore

    class Mutant(ObjectStore):
        def delete(self, kind, namespace, name):
            with self._lock:
                k = self._key(kind, namespace, name)
                if k not in self._objects:
                    raise NotFound(f"{kind} {namespace}/{name} not found")
                obj = self._objects.pop(k)
                self._notify(DELETED, kind, obj)
                return obj.deepcopy()

    s = Mutant()
    return Harness("mutant-delete-no-rv-bump", s,
                   watch_fn=lambda: s.watch(None))


def _mk_mutant_patch_drops_uid_pin() -> Harness:
    """Seeded bug: the patch verb silently discards the metadata.uid
    precondition — the incarnation guard every agent-tier status write
    rides (PR 2's authz-to-apply pin)."""
    from mpi_operator_tpu.machinery.store import ObjectStore

    class Mutant(ObjectStore):
        def patch(self, kind, namespace, name, patch, *, subresource=None):
            if isinstance(patch, dict) and isinstance(
                patch.get("metadata"), dict
            ):
                patch = dict(patch)
                patch["metadata"] = {
                    k: v for k, v in patch["metadata"].items() if k != "uid"
                }
                if not patch["metadata"]:
                    del patch["metadata"]
            return super().patch(kind, namespace, name, patch,
                                 subresource=subresource)

    s = Mutant()
    return Harness("mutant-patch-drops-uid-pin", s,
                   watch_fn=lambda: s.watch(None))


def _mk_mutant_update_ignores_rv() -> Harness:
    """Seeded bug: every update is silently forced — the lost-update
    clobber the rv precondition exists to prevent."""
    from mpi_operator_tpu.machinery.store import ObjectStore

    class Mutant(ObjectStore):
        def update(self, obj, force=False):
            return super().update(obj, force=True)

    s = Mutant()
    return Harness("mutant-update-ignores-rv", s,
                   watch_fn=lambda: s.watch(None))


def _mk_mutant_status_leaks_spec() -> Harness:
    """Seeded bug: the status subresource forgets to freeze spec/metadata
    (applies the patch as a plain merge) — the NODE-tier containment
    (patch-status-only) would silently stop containing."""
    from mpi_operator_tpu.machinery.store import ObjectStore

    class Mutant(ObjectStore):
        def patch(self, kind, namespace, name, patch, *, subresource=None):
            return super().patch(kind, namespace, name, patch,
                                 subresource=None)

    s = Mutant()
    return Harness("mutant-status-leaks-spec", s,
                   watch_fn=lambda: s.watch(None))


def _mk_mutant_batch_aborts_on_error() -> Harness:
    """Seeded bug: patch_batch stops applying at the first per-item error
    and fabricates NotFound for the suffix — breaking the applied-prefix
    + per-item-results contract (one dead pod's mirror would take the
    heartbeat riding behind it down with it)."""
    from mpi_operator_tpu.machinery.store import ObjectStore

    class Mutant(ObjectStore):
        def patch_batch(self, items):
            out: List[Any] = []
            failed = False
            for it in items:
                if failed:
                    out.append(NotFound("batch aborted"))
                    continue
                try:
                    out.append(self.patch(
                        it["kind"], it["namespace"], it["name"],
                        it.get("patch"), subresource=it.get("subresource"),
                    ))
                except _STORE_ERRORS as e:
                    out.append(e)
                    failed = True
            return out

    s = Mutant()
    return Harness("mutant-batch-aborts-on-error", s,
                   watch_fn=lambda: s.watch(None))


def _mk_mutant_ring_replays_past_dropped() -> Harness:
    """Seeded bug: the event ring serves an rv-anchored resume one event
    PAST the trim horizon (``rv < _dropped_rv - 1`` instead of
    ``rv < _dropped_rv``) — the replayed tail silently misses the trimmed
    event, exactly the lost-deletion class the 410-relist contract
    exists to prevent."""
    h = _mk_http()
    log = h.server._log
    orig = type(log).resume_after_rv

    def mutant_resume(rv):
        with log._cond:
            dropped = log._dropped_rv
        if dropped and rv == dropped - 1:
            # lie: pretend the ring still proves completeness here
            log._dropped_rv = dropped - 1
            try:
                return orig(log, rv)
            finally:
                log._dropped_rv = dropped
        return orig(log, rv)

    log.resume_after_rv = mutant_resume
    return Harness("mutant-ring-replays-past-dropped", h.store,
                   server=h.server, teardown=h.teardown,
                   watch_fn=h.watch_fn)


def _mk_mutant_replica_ack_before_majority() -> Harness:
    """Seeded REPLICATION bug: the leader acks a mutation after its own
    local commit without waiting for any follower to durably apply (the
    ack-before-majority window at its widest — shipping never happens).
    Reads ride followers, so the very first follower read (or the
    final-state list) after an acked write sees a store that 'lost' it —
    exactly what a leader crash inside that window would make permanent.
    No watch harness: the catch is the read path, and the detector must
    stay fast under ddmin re-execution."""
    rset, client, teardown = _mk_replica_parts()
    # the leader commits locally, ships nothing, acks
    rset.nodes["n0"]._replicate = lambda epoch, traced=False: None
    return Harness("mutant-replica-ack-before-majority", client,
                   teardown=teardown)


def _mk_mutant_replica_follower_regressed_rv() -> Harness:
    """Seeded REPLICATION bug: a follower serves a read from a stale
    snapshot of an incarnation it has already shown newer — the
    rv-REGRESSION the follower-read contract forbids (lag is legal,
    going backwards is not; a lister fed this would un-observe a
    committed transition)."""
    rset, client, teardown = _mk_replica_parts()

    class StickyReads:
        """First-read-wins cache per live incarnation: after any later
        write, get() still returns the old version at its old rv."""

        def __init__(self, inner):
            self._inner = inner
            self._cache: Dict[Any, Any] = {}

        def get(self, kind, namespace, name):
            obj = self._inner.get(kind, namespace, name)
            key = (kind, namespace, name)
            cached = self._cache.get(key)
            if cached is not None and cached.metadata.uid == obj.metadata.uid:
                return cached.deepcopy()
            self._cache[key] = obj.deepcopy()
            return obj

        def __getattr__(self, attr):
            return getattr(self._inner, attr)

    return Harness("mutant-replica-follower-regressed-rv",
                   StickyReads(client), teardown=teardown)


MUTANTS: Dict[str, Callable[[], Harness]] = {
    "delete-no-rv-bump": _mk_mutant_delete_no_rv_bump,
    "patch-drops-uid-pin": _mk_mutant_patch_drops_uid_pin,
    "update-ignores-rv": _mk_mutant_update_ignores_rv,
    "status-leaks-spec": _mk_mutant_status_leaks_spec,
    "batch-aborts-on-error": _mk_mutant_batch_aborts_on_error,
    "ring-replays-past-dropped": _mk_mutant_ring_replays_past_dropped,
    "replica-ack-before-majority": _mk_mutant_replica_ack_before_majority,
    "replica-follower-regressed-rv":
        _mk_mutant_replica_follower_regressed_rv,
}


# ---------------------------------------------------------------------------
# the differential run
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Divergence:
    backend: str
    op_index: int  # index into the EXECUTED subsequence (-1 = final state)
    where: str  # "result" | "watch" | "final-state"
    expected: str
    actual: str

    def render(self) -> str:
        at = ("final state" if self.op_index < 0
              else f"op[{self.op_index}] ({self.where})")
        return (
            f"{self.backend} diverged from the sequential model at {at}\n"
            f"    model:   {self.expected}\n"
            f"    backend: {self.actual}"
        )


def _short(v: Any, cap: int = 400) -> str:
    s = json.dumps(v, sort_keys=True, default=str)
    return s if len(s) <= cap else s[:cap] + "..."


def run_ops(
    factory: Callable[[], Harness],
    ops: List[Dict[str, Any]],
    *,
    check_watch: bool = True,
) -> Optional[Divergence]:
    """Execute a (sub)sequence of symbolic ops against one backend and the
    model in lockstep; return the FIRST divergence (or None). Fresh model
    and fresh backend per call — re-execution is what makes shrinking and
    replay sound."""
    model = ModelStore()
    h = factory()
    try:
        if check_watch:
            h.start_watch()
        for i, op in enumerate(ops):
            c = resolve(op, model)
            if c["op"] == "watch_resume" and h.server is None:
                continue  # ring resumes only exist on the wire seam
            want = _exec_model(model, c)
            got = _exec_backend(h, c)
            if want != got:
                return Divergence(h.name, i, "result", _short(want),
                                  _short(got))
            if check_watch and h.watch_q is not None:
                h.drain_watch(len(model.events))
                want_w = model.watch_stream()
                got_w = [list(t) for t in h.delivered]
                if [list(t) for t in want_w] != got_w:
                    return Divergence(
                        h.name, i, "watch",
                        _short([list(t) for t in want_w]), _short(got_w),
                    )
        # final state: every kind's full list must match the model exactly
        for kind in _KINDS:
            want_l = model.list(kind)
            got_l = [encode(o) for o in h.store.list(kind)]
            if want_l != got_l:
                return Divergence(h.name, -1, "final-state",
                                  _short(want_l), _short(got_l))
        return None
    finally:
        h.teardown()


# ---------------------------------------------------------------------------
# shrinking + tokens
# ---------------------------------------------------------------------------


def encode_token(seed: int, indices: List[int]) -> str:
    return f"{TOKEN_VERSION}:fuzz:{seed}:{','.join(map(str, indices))}"


def decode_token(token: str) -> Tuple[int, List[int]]:
    try:
        version, tag, seed, body = token.split(":", 3)
        if version != TOKEN_VERSION or tag != "fuzz":
            raise ValueError(f"not a {TOKEN_VERSION}:fuzz token")
        indices = [int(p) for p in body.split(",") if p]
        if not indices or indices != sorted(set(indices)):
            raise ValueError("indices must be strictly increasing")
        return int(seed), indices
    except ValueError as e:
        raise FuzzError(f"bad replay token {token!r}: {e}") from None


def ops_for_token(token: str) -> List[Dict[str, Any]]:
    seed, indices = decode_token(token)
    full = generate(seed, max(indices) + 1)
    return [full[i] for i in indices]


def shrink(
    factory: Callable[[], Harness],
    full: List[Dict[str, Any]],
    indices: List[int],
    *,
    check_watch: bool = True,
) -> List[int]:
    """ddmin-lite: greedily remove chunks (halving granularity) of the
    index set while the subsequence still diverges. Minimal in the 1-op
    removal sense — removing ANY single remaining op loses the repro.
    Probes skip the watch-stream diff unless the original divergence was
    a watch divergence (an op-result repro doesn't need the watch, and a
    probe without one skips the whole poller bootstrap/teardown)."""

    def fails(idx: List[int]) -> bool:
        return run_ops(
            factory, [full[i] for i in idx], check_watch=check_watch
        ) is not None

    n = 2
    while len(indices) >= 2:
        chunk = max(1, (len(indices) + n - 1) // n)
        reduced = False
        for start in range(0, len(indices), chunk):
            candidate = indices[:start] + indices[start + chunk:]
            if candidate and fails(candidate):
                indices = candidate
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if chunk <= 1:
                break
            n = min(n * 2, len(indices))
    return indices


# ---------------------------------------------------------------------------
# findings allowlist (.storecheck-allow, racecheck-allow precedence rules)
# ---------------------------------------------------------------------------


ALLOWLIST_FILENAME = ".storecheck-allow"


@dataclass(frozen=True)
class AllowRule:
    """One allowlist entry: ``<kind>:<spec>  <reason>``. ``kind`` is
    ``fuzz`` (spec matched as a substring of a divergence's rendered
    location, e.g. a backend name) or ``crash`` (``torn-tail`` gates the
    synchronous=NORMAL acked-loss class). ``reason`` is MANDATORY — an
    unexplained suppression is exactly the review smell this file exists
    to eliminate (same contract as .racecheck-allow)."""

    kind: str
    spec: str
    reason: str

    def matches(self, finding: Any) -> bool:
        if self.kind == "fuzz" and isinstance(finding, Divergence):
            return self.spec in f"{finding.backend}:{finding.where}"
        return False


def parse_allowlist(text: str,
                    path: str = ALLOWLIST_FILENAME) -> List[AllowRule]:
    """The shared allowlist grammar (analysis.allowlist, same core
    racecheck rides): blank lines and ``#`` comments skipped; a rule
    without a reason, or with an unknown kind, is a hard error."""
    return allowlist.parse_rules(text, path, ("fuzz", "crash"), AllowRule)


def load_allowlist(path: str) -> List[AllowRule]:
    with open(path, encoding="utf-8") as f:
        return parse_allowlist(f.read(), path)


def find_allowlist(start_dir: str) -> Optional[str]:
    """Nearest .storecheck-allow walking up from ``start_dir``, stopping
    at the repository boundary (shared resolution with racecheck: a stray
    allowlist ABOVE the checkout must not gate the torn-tail class)."""
    return allowlist.find_nearest(start_dir, ALLOWLIST_FILENAME)


# ---------------------------------------------------------------------------
# fuzz driver + reports
# ---------------------------------------------------------------------------


@dataclass
class FuzzFinding:
    backend: str
    seed: int
    token: str
    ops: List[Dict[str, Any]]
    divergence: Divergence

    def render(self) -> str:
        return (
            f"storecheck fuzz: {self.divergence.render()}\n"
            f"  minimal repro ({len(self.ops)} op(s)):\n"
            + "".join(f"    {json.dumps(o, sort_keys=True)}\n"
                      for o in self.ops)
            + f"  replay token: {self.token}"
        )


@dataclass
class FuzzReport:
    ok: bool
    sequences: int
    backends: List[str]
    finding: Optional[FuzzFinding] = None
    # allowlisted divergences, skipped-and-continued (racecheck's
    # "allowed findings print informationally" semantics): (seed,
    # divergence, gating reason)
    allowed: List[Tuple[int, Divergence, str]] = field(default_factory=list)

    def render(self) -> str:
        if self.ok:
            lines = [
                f"storecheck fuzz: ok — {self.sequences} sequence(s) over "
                f"{', '.join(self.backends)}: no divergence from the "
                f"sequential model"
            ]
        else:
            lines = [f"storecheck fuzz: FAILED\n{self.finding.render()}"]
        for s, div, reason in self.allowed:
            lines.append(
                f"  allowed (fuzz, seed {s}): {div.backend}:{div.where} "
                f"— {reason}"
            )
        return "\n".join(lines)


def fuzz(
    factories: Optional[Dict[str, Callable[[], Harness]]] = None,
    *,
    seed: int = 0,
    budget: FuzzBudget = DEFAULT_BUDGET,
    allowlist: Optional[List[AllowRule]] = None,
) -> FuzzReport:
    """Fuzz every backend in ``factories`` (default: the three real ones)
    within budget; on the first non-allowlisted divergence, shrink it,
    mint the replay token, verify twice-identical re-execution, and stop.
    A divergence an ``allowlist`` rule gates is recorded informationally
    and that (sequence, backend) pair is skipped — the REST of the budget
    still runs (a gated wire quirk must not hide a fresh sqlite bug later
    in the budget)."""
    factories = dict(factories or REAL_BACKENDS)
    runs = 0
    allowed: List[Tuple[int, Divergence, str]] = []
    for s in range(seed, seed + budget.sequences):
        full = generate(s, budget.ops)
        all_indices = list(range(len(full)))
        for name, factory in factories.items():
            runs += 1
            div = run_ops(factory, full)
            if div is None:
                continue
            gate = next(
                (r for r in (allowlist or [])
                 if r.kind == "fuzz" and r.matches(div)),
                None,
            )
            if gate is not None:
                allowed.append((s, div, gate.reason))
                continue
            # everything after the diverging op is noise: truncate before
            # ddmin (run_ops stops at the first divergence, so op_index
            # names a prefix of the executed sequence)
            prefix = (all_indices if div.op_index < 0
                      else all_indices[: div.op_index + 1])
            minimal = shrink(factory, full, prefix,
                             check_watch=div.where == "watch")
            token = encode_token(s, minimal)
            finding = replay(token, factory)
            if finding is None:
                # `token` is a v1:fuzz replay token (seed + op indices),
                # not a credential; printing it is the whole point of
                # deterministic replay — hence the SEC001 disable.
                raise FuzzError(
                    f"shrunk token {token} no longer "  # oplint: disable=SEC001
                    f"reproduces (nondeterministic divergence on {name}?)"
                )
            return FuzzReport(False, runs, sorted(factories),
                              finding=finding, allowed=allowed)
    return FuzzReport(True, runs, sorted(factories), allowed=allowed)


def replay(
    token: str,
    factory: Callable[[], Harness],
) -> Optional[FuzzFinding]:
    """Re-execute the exact subsequence a token encodes against one
    backend factory; returns the finding (or None when it runs clean)."""
    seed, indices = decode_token(token)
    ops = ops_for_token(token)
    div = run_ops(factory, ops)
    if div is None:
        return None
    return FuzzFinding(div.backend, seed, token, ops, div)


def fixture_for_mutant(name: str,
                       budget: FuzzBudget = DEFAULT_BUDGET) -> Dict[str, Any]:
    """Fuzz one seeded mutant to its minimal pinned repro — the JSON shape
    stored under tests/data/storecheck/ (regenerate a drifted corpus with
    :func:`mint_mutant_fixtures`)."""
    report = fuzz({name: MUTANTS[name]}, budget=budget)
    if report.ok:
        raise FuzzError(f"mutant {name} not caught within "
                        f"{budget.sequences}x{budget.ops}")
    f = report.finding
    return {
        "mutant": name,
        "token": f.token,
        "ops": f.ops,
        "divergence": {
            "backend": f.divergence.backend,
            "op_index": f.divergence.op_index,
            "where": f.divergence.where,
        },
    }


def mint_mutant_fixtures(outdir: str) -> List[str]:
    """(Re)write the pinned minimal-repro corpus: one JSON per seeded
    mutant. Run after a deliberate generator/model change::

        python -c "from mpi_operator_tpu.analysis.storecheck import \\
            mint_mutant_fixtures; mint_mutant_fixtures('tests/data/storecheck')"
    """
    os.makedirs(outdir, exist_ok=True)
    written = []
    for name in MUTANTS:
        path = os.path.join(outdir, f"{name}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(fixture_for_mutant(name), f, indent=2, sort_keys=True)
            f.write("\n")
        written.append(path)
    return written


def self_test(budget: FuzzBudget = DEFAULT_BUDGET) -> List[str]:
    """The fuzzer's acceptance gate: every seeded mutant is caught within
    the budget, its minimal repro replays twice-identically from the
    token, and the three real backends fuzz clean at the SAME budget —
    the exact run `python -m ... fuzz` performs at defaults, so the gate
    and the plain CLI can never disagree on what clean means."""
    failures: List[str] = []
    for name, factory in MUTANTS.items():
        report = fuzz({name: factory}, budget=budget)
        if report.ok:
            failures.append(
                f"seeded mutant {name} was NOT caught within budget "
                f"({budget.sequences}x{budget.ops})"
            )
            continue
        f = report.finding
        first = replay(f.token, factory)
        second = replay(f.token, factory)
        if first is None or second is None:
            failures.append(f"mutant {name}: token {f.token} did not "
                            f"replay to a divergence")
        elif first.divergence != second.divergence:
            failures.append(f"mutant {name}: token {f.token} replays "
                            f"diverged (nondeterminism)")
    clean = fuzz(seed=0, budget=budget)
    if not clean.ok:
        failures.append(
            "real backends must fuzz clean: " + clean.finding.render()
        )
    return failures
