"""Shared allowlist grammar + nearest-file resolution.

`.racecheck-allow` (racecheck) and `.storecheck-allow` (storecheck /
crashpoints) carry the same contract — ``<kind>:<spec>  <reason>`` lines,
reason MANDATORY, nearest file wins walking up from the start directory,
and the walk NEVER crosses a repository boundary (``.git`` /
``pytest.ini``): a stray allowlist in a home directory above the checkout
must not silently suppress findings. One implementation here, so the
grammar and the boundary rule cannot drift between the two tools; each
keeps its own ``AllowRule`` dataclass (the ``matches`` semantics differ)
and passes its constructor in as ``make_rule``.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, TypeVar

R = TypeVar("R")


def parse_rules(
    text: str,
    path: str,
    kinds: Sequence[str],
    make_rule: Callable[[str, str, str], R],
) -> List[R]:
    """Parse allowlist lines: ``<kind>:<spec>  <reason...>``. Blank lines
    and ``#`` comments are skipped; a rule without a reason, or with a
    kind outside ``kinds``, is a hard error — the file's contract is that
    every deliberate exception names WHY it is deliberate."""
    rules: List[R] = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        head, _, reason = line.partition(" ")
        kind, sep, spec = head.partition(":")
        if not sep or not spec:
            raise ValueError(
                f"{path}:{lineno}: expected '<kind>:<spec> <reason>', "
                f"got {line!r}"
            )
        if kind not in kinds:
            raise ValueError(
                f"{path}:{lineno}: unknown finding kind {kind!r} "
                f"({' | '.join(kinds)})"
            )
        reason = reason.strip()
        if not reason:
            raise ValueError(
                f"{path}:{lineno}: allowlist entry {head!r} carries no "
                f"reason — every deliberate exception must say why"
            )
        rules.append(make_rule(kind, spec, reason))
    return rules


def find_nearest(start_dir: str, filename: str) -> Optional[str]:
    """Walk up from ``start_dir`` to the nearest ``filename`` (the same
    nearest-wins resolution as pytest's rootdir), but never PAST a
    repository boundary (.git / pytest.ini)."""
    d = os.path.abspath(start_dir)
    while True:
        cand = os.path.join(d, filename)
        if os.path.isfile(cand):
            return cand
        if os.path.exists(os.path.join(d, ".git")) or os.path.isfile(
            os.path.join(d, "pytest.ini")
        ):
            return None  # repo root reached without an allowlist
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent
