"""opcheck linearizability: record store histories, check them against the
sequential spec.

The store contract PRs 1-4 grew — rv-preconditioned optimistic concurrency,
uid-pinned incarnation writes, the frozen status subresource, write-once
terminal phases, watch events in commit order — is a SEQUENTIAL
specification. Whether the three backends actually provide it to
*concurrent* callers is a linearizability question (Herlihy & Wing): does
every recorded call/return history admit a total order of the operations,
consistent with real time, under which each result matches the sequential
model?

Three pieces, after Jepsen/Porcupine:

- **Recorder** (:class:`Recorder`): wraps the five store verbs
  (get/update/patch/create/delete) at the CLASS level on all three
  backends plus watch delivery (the consumer side of ``watch()`` queues),
  stamping each op with a global call/return sequence. Installed for a
  whole pytest session by :mod:`pytest_linearize`, so REAL suites
  (test_patch, test_stress) produce checkable histories.
- **Sequential model** (:class:`StoreModel`): per-key state (exists, rv,
  uid, phase) and the legality of each op's recorded result against it —
  Conflict iff the rv precondition misses, uid pins, AlreadyExists math,
  and Pod status-subresource terminal write-once.
- **Checker** (:func:`check`): Wing & Gong search for a valid
  linearization, partitioned per object key (sound: the store serializes
  per key and the global rv order is checked separately), with
  memoization on the linearized-set (state is a function of the set —
  every successful write records its resulting rv, so "latest applied
  write" determines the state). Watch streams are checked per
  (stream, key) for rv monotonicity — delivery must follow linearization
  order. On violation the error carries the **minimal violating prefix**
  (shortest return-ordered prefix that is itself non-linearizable), which
  is what makes a flagged history debuggable.
"""

from __future__ import annotations

import json
import threading
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# the sequential spec lives in analysis/model.py (promoted there so the
# differential fuzzer and this checker share ONE model); the old names
# stay importable here — tests and tools address the spec through either
from mpi_operator_tpu.analysis.model import (  # noqa: F401  (re-exports)
    INITIAL as _INITIAL,
    STATE_ERRORS as _STATE_ERRORS,
    TERMINAL_PHASES,
    State as _State,
    StoreModel,
)


@dataclass
class OpRecord:
    op_id: int
    thread: int
    store: str  # per-store-instance tag: histories never mix backends
    op: str  # get | update | patch | create | delete
    kind: str
    namespace: str
    name: str
    call_seq: int
    ret_seq: int
    args: Dict[str, Any] = field(default_factory=dict)
    result: Dict[str, Any] = field(default_factory=dict)

    def key(self) -> Tuple[str, str, str, str]:
        return (self.store, self.kind, self.namespace, self.name)

    def render(self) -> str:
        args = ", ".join(f"{k}={v!r}" for k, v in sorted(self.args.items()))
        if "error" in self.result:
            res = f"raise {self.result['error']}"
        else:
            res = f"rv={self.result.get('rv')}"
        return (
            f"[{self.op_id}] t{self.thread % 10000} "
            f"{self.op}({self.kind} {self.namespace}/{self.name}"
            f"{', ' + args if args else ''}) -> {res} "
            f"[call={self.call_seq} ret={self.ret_seq}]"
        )


@dataclass
class WatchRecord:
    stream: str
    seq: int
    etype: str
    kind: str
    namespace: str
    name: str
    rv: int

    def render(self) -> str:
        return (
            f"[{self.seq}] watch {self.stream}: {self.etype} "
            f"{self.kind} {self.namespace}/{self.name} rv={self.rv}"
        )


@dataclass
class History:
    ops: List[OpRecord] = field(default_factory=list)
    watch: List[WatchRecord] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(
            {
                "ops": [o.__dict__ for o in self.ops],
                "watch": [w.__dict__ for w in self.watch],
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "History":
        data = json.loads(text)
        return cls(
            ops=[OpRecord(**o) for o in data.get("ops", [])],
            watch=[WatchRecord(**w) for w in data.get("watch", [])],
        )


# ---------------------------------------------------------------------------
# the checker (Wing & Gong per key; the sequential model is
# analysis.model.StoreModel, shared with the differential fuzzer)
# ---------------------------------------------------------------------------


_SEARCH_NODE_CAP = 500_000


class Inconclusive(RuntimeError):
    """Search exceeded the node cap — a pathological history, not a
    verdict. Real control-plane histories are near-sequential and never
    get close."""


def _linearize_ops(ops: List[OpRecord]) -> bool:
    """True iff ``ops`` (one key's complete call/return history) admits a
    valid linearization. Iterative Wing & Gong: candidates are pending ops
    whose call precedes every pending return; memoized on the pending
    set (per-key state is a function of the applied set — each successful
    write pins its resulting rv, so 'the applied write with max rv'
    determines the state regardless of application order)."""
    ops = sorted(ops, key=lambda o: o.call_seq)
    n = len(ops)
    if n == 0:
        return True
    seen: set = set()
    nodes = 0

    def candidates(pending: frozenset) -> List[int]:
        m = min(ops[i].ret_seq for i in pending)
        return [i for i in sorted(pending) if ops[i].call_seq < m]

    start = frozenset(range(n))
    stack: List[Tuple[frozenset, _State, List[int], int]] = [
        (start, _INITIAL, candidates(start), 0)
    ]
    while stack:
        pending, state, cands, ci = stack[-1]
        if not pending:
            return True
        if ci >= len(cands):
            stack.pop()
            continue
        stack[-1] = (pending, state, cands, ci + 1)
        nodes += 1
        if nodes > _SEARCH_NODE_CAP:
            raise Inconclusive(
                f"linearization search exceeded {_SEARCH_NODE_CAP} nodes "
                f"over {n} ops"
            )
        i = cands[ci]
        nxt = StoreModel.apply(state, ops[i])
        if nxt is None:
            continue
        rest = pending - {i}
        if rest in seen:
            continue
        seen.add(rest)
        if not rest:
            return True
        stack.append((rest, nxt, candidates(rest), 0))
    return False


@dataclass
class Violation:
    key: Tuple[str, str, str, str]
    message: str
    prefix: List[str]  # rendered minimal violating prefix

    def render(self) -> str:
        store, kind, ns, name = self.key
        head = f"{kind} {ns}/{name} (store {store}): {self.message}"
        return head + "".join("\n    " + line for line in self.prefix)


@dataclass
class CheckReport:
    ok: bool
    violations: List[Violation]
    keys: int
    ops: int
    watch_events: int

    def render(self) -> str:
        if self.ok:
            return (
                f"linearize: ok — {self.ops} op(s) over {self.keys} key(s), "
                f"{self.watch_events} watch event(s), every history "
                f"linearizable"
            )
        lines = [f"linearize: {len(self.violations)} violation(s)"]
        lines += ["  " + v.render().replace("\n", "\n  ") for v in self.violations]
        return "\n".join(lines)


def _minimal_prefix(ops: List[OpRecord]) -> List[OpRecord]:
    """Shortest return-ordered prefix of a non-linearizable key history
    that is itself non-linearizable — the debuggable core of a flagged
    history."""
    by_ret = sorted(ops, key=lambda o: o.ret_seq)
    for k in range(1, len(by_ret) + 1):
        if not _linearize_ops(by_ret[:k]):
            return by_ret[:k]
    return by_ret  # unreachable if caller verified non-linearizability


def check(history: History) -> CheckReport:
    """Check a recorded history against the store spec. Per-key
    linearizability + per-(stream, key) watch rv monotonicity."""
    per_key: Dict[Tuple[str, str, str, str], List[OpRecord]] = {}
    for op in history.ops:
        per_key.setdefault(op.key(), []).append(op)
    violations: List[Violation] = []
    for key, ops in sorted(per_key.items()):
        try:
            if _linearize_ops(ops):
                continue
        except Inconclusive as e:
            violations.append(Violation(key, f"INCONCLUSIVE: {e}", []))
            continue
        prefix = _minimal_prefix(ops)
        violations.append(
            Violation(
                key,
                f"no valid linearization; minimal violating prefix "
                f"({len(prefix)} of {len(ops)} ops):",
                [o.render() for o in prefix],
            )
        )
    # watch order: per (stream, key), delivered rvs may never regress —
    # delivery must follow linearization (= commit) order. Non-strict:
    # relist recovery legally re-delivers the current version.
    streams: Dict[Tuple[str, Tuple[str, str, str]], List[WatchRecord]] = {}
    for w in history.watch:
        streams.setdefault(
            (w.stream, (w.kind, w.namespace, w.name)), []
        ).append(w)
    for (stream, (kind, ns, name)), events in sorted(streams.items()):
        events = sorted(events, key=lambda w: w.seq)
        high = 0
        for idx, w in enumerate(events):
            if w.rv < high:
                prefix = [e.render() for e in events[: idx + 1]]
                violations.append(
                    Violation(
                        (stream, kind, ns, name),
                        f"watch delivered rv {w.rv} after rv {high} "
                        f"(events out of linearization order); minimal "
                        f"violating prefix ({idx + 1} events):",
                        prefix,
                    )
                )
                break
            high = max(high, w.rv)
    return CheckReport(
        ok=not violations,
        violations=violations,
        keys=len(per_key),
        ops=len(history.ops),
        watch_events=len(history.watch),
    )


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------


def _obj_rv(obj: Any) -> Optional[int]:
    try:
        return obj.metadata.resource_version
    except AttributeError:
        return None


def _obj_result(obj: Any) -> Dict[str, Any]:
    out: Dict[str, Any] = {"rv": _obj_rv(obj)}
    try:
        out["uid"] = obj.metadata.uid
    except AttributeError:
        pass
    ph = getattr(getattr(obj, "status", None), "phase", None)
    if ph is not None:
        out["phase"] = str(ph)
    return out


class _RecordingQueue:
    """Wraps a store watch queue: every event DELIVERED to the consumer is
    stamped into the history (delivery, not enqueue, is the moment that
    must respect linearization order from the consumer's view)."""

    def __init__(self, inner: Any, recorder: "Recorder", stream: str):
        self._inner = inner
        self._recorder = recorder
        self._stream = stream

    def get(self, *a, **k):
        ev = self._inner.get(*a, **k)
        self._recorder.record_watch(self._stream, ev)
        return ev

    def get_nowait(self):
        ev = self._inner.get_nowait()
        self._recorder.record_watch(self._stream, ev)
        return ev

    def __getattr__(self, name):
        return getattr(self._inner, name)


_TAG_ATTR = "_opcheck_store_tag"


class Recorder:
    """Class-level instrumentation of the store verbs; one Recorder owns
    one History spanning every store instance touched while installed
    (ops carry a per-instance tag, so the checker never mixes them)."""

    VERBS = ("get", "update", "patch", "create", "delete")

    def __init__(self):
        self._mu = threading.Lock()
        self._seq = 0
        self.history = History()
        self._patched: List[Tuple[type, str, Any]] = []

    # -- sequencing ---------------------------------------------------------

    def _next_seq(self) -> int:
        with self._mu:
            self._seq += 1
            return self._seq

    def _tag(self, store: Any) -> str:
        tag = getattr(store, _TAG_ATTR, None)
        if tag is None:
            tag = f"{type(store).__name__}-{_uuid.uuid4().hex[:6]}"
            try:
                setattr(store, _TAG_ATTR, tag)
            except AttributeError:
                tag = f"{type(store).__name__}-shared"
        return tag

    def record_op(
        self,
        store: Any,
        op: str,
        kind: str,
        namespace: str,
        name: str,
        args: Dict[str, Any],
        fn,
    ):
        call_seq = self._next_seq()
        try:
            out = fn()
        except Exception as e:
            ret_seq = self._next_seq()
            with self._mu:
                self.history.ops.append(
                    OpRecord(
                        len(self.history.ops), threading.get_ident(),
                        self._tag(store), op, kind, namespace, name,
                        call_seq, ret_seq, args,
                        {"error": type(e).__name__},
                    )
                )
            raise
        ret_seq = self._next_seq()
        result = _obj_result(out) if out is not None else {}
        with self._mu:
            self.history.ops.append(
                OpRecord(
                    len(self.history.ops), threading.get_ident(),
                    self._tag(store), op, kind, namespace, name,
                    call_seq, ret_seq, args, result,
                )
            )
        return out

    def record_watch(self, stream: str, ev: Any) -> None:
        obj = getattr(ev, "obj", None)
        if obj is None:
            return  # relist markers etc.: not a watch event
        rv = _obj_rv(obj)
        if rv is None:
            return
        m = obj.metadata
        with self._mu:
            self._seq += 1
            self.history.watch.append(
                WatchRecord(
                    stream, self._seq, ev.type, ev.kind, m.namespace,
                    m.name, rv,
                )
            )

    # -- class patching -----------------------------------------------------

    def _wrap_verb(self, cls: type, verb: str) -> None:
        orig = cls.__dict__.get(verb)
        if orig is None:
            return
        rec = self

        if verb == "get":
            def wrapped(self, kind, namespace, name):  # noqa: ANN001
                return rec.record_op(
                    self, "get", kind, namespace, name, {},
                    lambda: orig(self, kind, namespace, name),
                )
        elif verb == "delete":
            def wrapped(self, kind, namespace, name):  # noqa: ANN001
                return rec.record_op(
                    self, "delete", kind, namespace, name, {},
                    lambda: orig(self, kind, namespace, name),
                )
        elif verb == "update":
            def wrapped(self, obj, force=False):  # noqa: ANN001
                m = obj.metadata
                return rec.record_op(
                    self, "update", obj.kind, m.namespace, m.name,
                    {"rv": m.resource_version, "force": bool(force)},
                    lambda: orig(self, obj, force),
                )
        elif verb == "create":
            def wrapped(self, obj):  # noqa: ANN001
                m = obj.metadata
                return rec.record_op(
                    self, "create", obj.kind, m.namespace, m.name, {},
                    lambda: orig(self, obj),
                )
        else:  # patch
            def wrapped(self, kind, namespace, name, patch,  # noqa: ANN001
                        *, subresource=None):
                meta = (
                    patch.get("metadata") if isinstance(patch, dict) else None
                )
                args: Dict[str, Any] = {"subresource": subresource}
                if isinstance(meta, dict):
                    if meta.get("resource_version") is not None:
                        args["precond_rv"] = meta["resource_version"]
                    if meta.get("uid") is not None:
                        args["precond_uid"] = meta["uid"]
                return rec.record_op(
                    self, "patch", kind, namespace, name, args,
                    lambda: orig(self, kind, namespace, name, patch,
                                 subresource=subresource),
                )

        wrapped.__name__ = verb
        setattr(cls, verb, wrapped)
        self._patched.append((cls, verb, orig))

    def _wrap_patch_batch(self, cls: type) -> None:
        """Only the HTTP client needs this: its patch_batch is ONE wire
        request that never routes through the wrapped ``patch`` verb (the
        in-process backends loop through ``self.patch`` and are already
        recorded). Each item becomes an op sharing the batch's call/return
        window — the checker may order them freely within it, which is
        exactly the server's freedom too."""
        orig = cls.__dict__.get("patch_batch")
        if orig is None:
            return
        rec = self

        def patch_batch(self, items):  # noqa: ANN001
            call_seq = rec._next_seq()
            out = orig(self, items)  # whole-batch failure: nothing committed
            ret_seq = rec._next_seq()
            tag = rec._tag(self)
            ident = threading.get_ident()
            with rec._mu:
                for it, res in zip(items, out):
                    patch = it.get("patch")
                    meta = (
                        patch.get("metadata")
                        if isinstance(patch, dict) else None
                    )
                    args: Dict[str, Any] = {
                        "subresource": it.get("subresource"),
                    }
                    if isinstance(meta, dict):
                        if meta.get("resource_version") is not None:
                            args["precond_rv"] = meta["resource_version"]
                        if meta.get("uid") is not None:
                            args["precond_uid"] = meta["uid"]
                    result = (
                        {"error": type(res).__name__}
                        if isinstance(res, Exception) else _obj_result(res)
                    )
                    rec.history.ops.append(
                        OpRecord(
                            len(rec.history.ops), ident, tag, "patch",
                            it["kind"], it["namespace"], it["name"],
                            call_seq, ret_seq, args, result,
                        )
                    )
            return out

        patch_batch.__name__ = "patch_batch"
        setattr(cls, "patch_batch", patch_batch)
        self._patched.append((cls, "patch_batch", orig))

    def _wrap_watch(self, cls: type) -> None:
        orig_watch = cls.__dict__.get("watch")
        orig_stop = cls.__dict__.get("stop_watch")
        if orig_watch is None:
            return
        rec = self

        def watch(self, kind=None):  # noqa: ANN001
            q = orig_watch(self, kind)
            stream = f"{rec._tag(self)}/w{rec._next_seq()}"
            return _RecordingQueue(q, rec, stream)

        def stop_watch(self, q):  # noqa: ANN001
            if isinstance(q, _RecordingQueue):
                q = q._inner
            return orig_stop(self, q)

        watch.__name__ = "watch"
        setattr(cls, "watch", watch)
        self._patched.append((cls, "watch", orig_watch))
        if orig_stop is not None:
            stop_watch.__name__ = "stop_watch"
            setattr(cls, "stop_watch", stop_watch)
            self._patched.append((cls, "stop_watch", orig_stop))

    def install(self, classes=None, batch_classes=None) -> "Recorder":
        """Default: instrument the three store backends. ``classes``
        restricts recording to other store-surfaced classes (e.g. the
        replica set's ReplicaClient facade, so every node's ops share ONE
        history tag); ``batch_classes`` names which of those own a
        patch_batch that does NOT loop through their wrapped ``patch``
        (the in-process backends' loop is already recorded per item —
        wrapping both would double-record)."""
        from mpi_operator_tpu.machinery.http_store import HttpStoreClient
        from mpi_operator_tpu.machinery.sqlite_store import SqliteStore
        from mpi_operator_tpu.machinery.store import ObjectStore

        if classes is None:
            classes = (ObjectStore, SqliteStore, HttpStoreClient)
            batch_classes = (HttpStoreClient,)
        for cls in classes:
            for verb in self.VERBS:
                self._wrap_verb(cls, verb)
            self._wrap_watch(cls)
        for cls in (batch_classes or ()):
            self._wrap_patch_batch(cls)
        return self

    def uninstall(self) -> None:
        while self._patched:
            cls, name, orig = self._patched.pop()
            setattr(cls, name, orig)


# ---------------------------------------------------------------------------
# seeded violation histories (the negative fixtures)
# ---------------------------------------------------------------------------


def _op(op_id, op, call, ret, args=None, result=None, *, thread=0,
        kind="Pod", name="p") -> OpRecord:
    return OpRecord(
        op_id, thread, "seed", op, kind, "default", name, call, ret,
        dict(args or {}), dict(result or {}),
    )


def seeded_violation_histories() -> Dict[str, History]:
    """The three canonical bad histories (ISSUE 5 satellite). Each MUST be
    flagged by :func:`check` — they are the checker's own acceptance
    fixtures, also shipped as JSON under tests/data/linearize/."""
    lost_update = History(ops=[
        _op(0, "create", 1, 2, {}, {"rv": 1, "uid": "u1"}),
        _op(1, "get", 3, 4, {}, {"rv": 1, "uid": "u1"}, thread=1),
        _op(2, "get", 5, 6, {}, {"rv": 1, "uid": "u1"}, thread=2),
        _op(3, "update", 7, 8, {"rv": 1, "force": False},
            {"rv": 2, "uid": "u1"}, thread=1),
        # the violation: this update's rv=1 precondition was consumed by
        # op 3, yet the store reported SUCCESS — a lost update
        _op(4, "update", 9, 10, {"rv": 1, "force": False},
            {"rv": 3, "uid": "u1"}, thread=2),
    ])
    stale_read = History(ops=[
        _op(0, "create", 1, 2, {}, {"rv": 1, "uid": "u1"}),
        _op(1, "update", 3, 4, {"rv": 1, "force": False},
            {"rv": 2, "uid": "u1"}),
        # the violation: invoked AFTER the rv=2 write returned (acked),
        # yet observed the overwritten rv=1 state
        _op(2, "get", 5, 6, {}, {"rv": 1, "uid": "u1"}, thread=1),
    ])
    watch_reorder = History(
        ops=[
            _op(0, "create", 1, 2, {}, {"rv": 1, "uid": "u1"}),
            _op(1, "update", 3, 4, {"rv": 1, "force": False}, {"rv": 2}),
            _op(2, "update", 5, 6, {"rv": 2, "force": False}, {"rv": 3}),
        ],
        watch=[
            WatchRecord("seed/w1", 7, "ADDED", "Pod", "default", "p", 1),
            # the violation: rv 3 delivered before rv 2 on one stream
            WatchRecord("seed/w1", 8, "MODIFIED", "Pod", "default", "p", 3),
            WatchRecord("seed/w1", 9, "MODIFIED", "Pod", "default", "p", 2),
        ],
    )
    return {
        "lost-update": lost_update,
        "stale-read-after-ack": stale_read,
        "watch-event-reordering": watch_reorder,
    }


def self_test() -> List[str]:
    """The checker's acceptance gate: every seeded violation history is
    flagged (with a minimal violating prefix), and a legal concurrent
    history — where the losing writer correctly Conflicts — checks clean."""
    failures: List[str] = []
    for name, hist in seeded_violation_histories().items():
        report = check(hist)
        if report.ok:
            failures.append(f"seeded {name} history was NOT flagged")
        elif not any(v.prefix for v in report.violations):
            failures.append(
                f"seeded {name} violation carries no minimal prefix"
            )
    clean = History(ops=[
        _op(0, "create", 1, 2, {}, {"rv": 1, "uid": "u1"}),
        _op(1, "get", 3, 5, {}, {"rv": 1, "uid": "u1"}, thread=1),
        _op(2, "get", 4, 6, {}, {"rv": 1, "uid": "u1"}, thread=2),
        _op(3, "update", 7, 10, {"rv": 1, "force": False},
            {"rv": 2, "uid": "u1"}, thread=1),
        # overlapping loser: correctly Conflicts — linearizable
        _op(4, "update", 8, 11, {"rv": 1, "force": False},
            {"error": "Conflict"}, thread=2),
        _op(5, "patch", 12, 13,
            {"subresource": "status", "precond_uid": "u1"},
            {"rv": 3, "uid": "u1", "phase": "Running"}, thread=1),
    ])
    report = check(clean)
    if not report.ok:
        failures.append(
            "legal concurrent history was falsely flagged: "
            + report.render()
        )
    return failures
