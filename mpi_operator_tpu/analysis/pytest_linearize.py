"""Opt-in pytest plugin recording store histories and checking them for
linearizability at session end.

Usage (the replay jobs; see README "Model checking the control plane"):

    python -m pytest tests/test_patch.py -q \\
        -p mpi_operator_tpu.analysis.pytest_linearize --linearize

With ``--linearize`` the five store verbs on all three backends (plus
watch delivery) are class-level instrumented for the whole session; at
session end the recorded history is checked against the sequential store
spec (mpi_operator_tpu.analysis.linearize) and ANY violation fails the
run, printing its minimal violating prefix. Without the flag the plugin
is inert, so it is always safe to load.
"""

from __future__ import annotations


def pytest_addoption(parser):
    parser.addoption(
        "--linearize", action="store_true", default=False,
        help="record every store op and check the session's history for "
             "linearizability (mpi_operator_tpu.analysis.linearize)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "linearize: tests exercising (or exercised under) the history "
        "recorder + linearizability checker",
    )
    if config.getoption("--linearize"):
        from mpi_operator_tpu.analysis import linearize

        config._linearize_recorder = linearize.Recorder().install()


def pytest_sessionfinish(session, exitstatus):
    rec = getattr(session.config, "_linearize_recorder", None)
    if rec is None:
        return
    rec.uninstall()
    from mpi_operator_tpu.analysis import linearize

    report = linearize.check(rec.history)
    session.config._linearize_report = report
    if not report.ok and exitstatus == 0:
        session.exitstatus = 1


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    report = getattr(config, "_linearize_report", None)
    if report is None:
        return
    terminalreporter.section("linearize")
    terminalreporter.write_line(report.render())
