"""racecheck: a mini-TSan for the control plane's threads.

The repo now has 11 modules sharing state under ``threading.Lock`` (store,
cache, workqueue, agent, controller, chaos proxy). This module is the
runtime half of the correctness-tooling layer: it observes REAL executions
(the existing controller/cache/stress tests) and flags

- **lock-order cycles**: per-thread lock acquisition stacks feed a directed
  acquired-while-holding graph; any cycle among lock instances is a
  potential deadlock even if this run happened not to interleave into it;
- **unguarded shared writes** (a lockset/Eraser variant): attribute
  accesses on instrumented control-plane classes record the set of tracked
  locks held; an attribute rebound by one thread under NO common lock while
  other threads access it is reported with the offending site.

Instrumentation is monitoring-based, not settrace-based: ``install()``
replaces the ``threading.Lock``/``threading.RLock`` factories so every lock
*constructed during the window* is a tracked wrapper (all control-plane
locks are created in ``__init__``, so patching before construction covers
them; import-time stdlib locks predate the window and are invisible —
documented, acceptable), and ``instrument_class`` wraps
``__getattribute__``/``__setattr__`` of the target classes to attribute
reads/writes of their state attributes to threads + locksets. This is
deterministic and has none of settrace's opcode-level cost; the trade-off
is that in-place container mutation (``self._queue.append``) is observed as
a read of the attribute, so the write-detection precision is on attribute
REBINDS — which is exactly where the control plane's flag/cursor state
(``_shutdown``, ``_cursor``, ``_synced``) lives.

False-positive control (why this does not spam on ownership handoff): the
Eraser state machine ignores the thread-exclusive phase (constructor
writes), and only reports once the attribute has been touched by **two
distinct threads in the shared phase** with an empty common lockset and at
least one shared-phase write — a started thread that simply inherits sole
ownership of its parent's fields (HttpStoreClient._cursor) never has a
second shared-phase accessor and stays silent.

Opt-in pytest wiring: ``-p mpi_operator_tpu.analysis.pytest_racecheck
--racecheck`` (see pytest_racecheck.py); findings fail the run.
"""

from __future__ import annotations

import os
import sys
import threading
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

from mpi_operator_tpu.analysis import allowlist

ALLOWLIST_FILENAME = ".racecheck-allow"

# the REAL factories, captured at import: the wrappers build on these and
# uninstall() restores them
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_THIS_FILE = __file__


def _caller_site() -> str:
    """file:line of the nearest frame outside this module (the acquisition
    or construction site a finding should point at)."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == _THIS_FILE:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


@dataclass(frozen=True)
class LockOrderFinding:
    cycle: Tuple[str, ...]  # lock labels, in cycle order
    edges: Tuple[str, ...]  # "A -> B (acquired at site)" strings

    def render(self) -> str:
        return (
            "lock-order cycle: " + " -> ".join(self.cycle)
            + "\n    " + "\n    ".join(self.edges)
        )


@dataclass(frozen=True)
class SharedStateFinding:
    cls: str
    attr: str
    site: str
    threads: int

    def render(self) -> str:
        return (
            f"unguarded shared state: {self.cls}.{self.attr} written with no "
            f"common lock across {self.threads} threads (at {self.site})"
        )


class LockTracker:
    """Per-thread held-lock stacks + the acquired-while-holding graph."""

    def __init__(self):
        self._mu = _REAL_LOCK()  # real: the tracker must not track itself
        self._tls = threading.local()
        # id(lock) -> label ("Lock@file:line" of the construction site)
        self.labels: Dict[int, str] = {}
        # (id(held), id(acquired)) -> acquisition site of the first sighting
        self.edges: Dict[Tuple[int, int], str] = {}

    # -- per-thread state ---------------------------------------------------

    def _held(self) -> List[Any]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_ids(self) -> FrozenSet[int]:
        return frozenset(id(l) for l in self._held())

    # -- events -------------------------------------------------------------

    def note_created(self, lock: Any, kind: str) -> None:
        with self._mu:
            self.labels[id(lock)] = f"{kind}@{_caller_site()}"

    def note_acquired(self, lock: Any) -> None:
        held = self._held()
        if not any(h is lock for h in held):  # reentrant re-acquire: no edge
            new_edges = []
            for h in held:
                key = (id(h), id(lock))
                if key not in self.edges:
                    new_edges.append(key)
            if new_edges:
                site = _caller_site()
                with self._mu:
                    for key in new_edges:
                        self.edges.setdefault(key, site)
        held.append(lock)

    def note_released(self, lock: Any) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def note_released_all(self, lock: Any) -> int:
        """Condition.wait's _release_save: the lock is fully released
        regardless of recursion depth. Returns the removed count so
        _acquire_restore can rebalance."""
        held = self._held()
        n = len(held)
        held[:] = [h for h in held if h is not lock]
        return n - len(held)

    # -- analysis -----------------------------------------------------------

    def cycles(self) -> List[LockOrderFinding]:
        """Cycles in the acquired-while-holding graph (Tarjan SCCs; any SCC
        with more than one node — or a self-edge — is a potential deadlock
        interleaving)."""
        with self._mu:
            edges = dict(self.edges)
            labels = dict(self.labels)
        graph: Dict[int, Set[int]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())

        index: Dict[int, int] = {}
        low: Dict[int, int] = {}
        on_stack: Set[int] = set()
        stack: List[int] = []
        sccs: List[List[int]] = []
        counter = [0]

        def strongconnect(v: int) -> None:
            # iterative Tarjan (the controller tests spawn deep chains)
            work = [(v, iter(graph[v]))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(graph[w])))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)

        for v in graph:
            if v not in index:
                strongconnect(v)

        out: List[LockOrderFinding] = []
        for scc in sccs:
            members = set(scc)
            cyclic = len(scc) > 1 or any(
                (v, v) in edges for v in scc
            )
            if not cyclic:
                continue
            names = tuple(labels.get(v, f"lock#{v}") for v in scc)
            edge_strs = tuple(
                f"{labels.get(a, a)} -> {labels.get(b, b)} (acquired at {site})"
                for (a, b), site in edges.items()
                if a in members and b in members
            )
            out.append(LockOrderFinding(names, edge_strs))
        return out


class TrackedLock:
    """threading.Lock wrapper feeding a LockTracker. Deliberately does NOT
    expose _release_save/_acquire_restore/_is_owned: threading.Condition
    then uses its plain release/acquire fallback, which routes through this
    wrapper and keeps the held-set honest."""

    __slots__ = ("_inner", "_tracker")

    def __init__(self, tracker: LockTracker):
        self._inner = _REAL_LOCK()
        self._tracker = tracker
        tracker.note_created(self, "Lock")

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._tracker.note_acquired(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._tracker.note_released(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class TrackedRLock:
    """threading.RLock wrapper. DOES implement the Condition protocol
    (_release_save/_acquire_restore/_is_owned) with tracking semantics —
    without them, Condition's acquire(0) ownership probe would succeed on a
    reentrant lock we own and misread it as un-owned."""

    __slots__ = ("_inner", "_tracker")

    def __init__(self, tracker: LockTracker):
        self._inner = _REAL_RLOCK()
        self._tracker = tracker
        tracker.note_created(self, "RLock")

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._tracker.note_acquired(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._tracker.note_released(self)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def _release_save(self):
        state = self._inner._release_save()
        self._tracker.note_released_all(self)
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        self._tracker.note_acquired(self)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


# ---------------------------------------------------------------------------
# shared-state monitor (lockset / Eraser variant)
# ---------------------------------------------------------------------------


@dataclass
class _KeyState:
    first_thread: Optional[int] = None
    shared: bool = False
    lockset: FrozenSet[int] = frozenset()
    shared_threads: Set[int] = field(default_factory=set)
    write_in_shared: bool = False
    reported: bool = False
    # site of the last LOCKLESS shared-phase write: the finding must point
    # at the offending writer, not at whichever (possibly correctly locked)
    # access happened to trip the report threshold
    write_site: str = ""
    # identity guard: keys are id(obj)-based and ids are REUSED after GC —
    # without this, a new object allocated at a dead one's address inherits
    # its accessor history and the constructor write reads as a cross-thread
    # race (the exact false positive the first cache+stress replay hit)
    ref: Any = None


class SharedStateMonitor:
    def __init__(self, tracker: LockTracker):
        self._tracker = tracker
        self._mu = _REAL_LOCK()
        self._keys: Dict[Tuple[int, str], _KeyState] = {}
        self._tls = threading.local()
        self.findings: List[SharedStateFinding] = []
        self._instrumented: List[Tuple[type, Any, Any]] = []

    def record(self, obj: Any, attr: str, is_write: bool) -> None:
        if getattr(self._tls, "busy", False):
            return
        self._tls.busy = True
        try:
            tid = threading.get_ident()
            held = self._tracker.held_ids()
            key = (id(obj), attr)
            with self._mu:
                st = self._keys.get(key)
                if st is not None and (st.ref is None or st.ref() is not obj):
                    st = None  # id reused by a new object: fresh history
                if st is None:
                    try:
                        ref = weakref.ref(obj)
                    except TypeError:
                        ref = None  # unweakrefable: accept the reuse risk
                    st = self._keys[key] = _KeyState(first_thread=tid, ref=ref)
                if st.reported:
                    return
                if not st.shared:
                    if tid == st.first_thread:
                        return  # thread-exclusive phase (constructor writes)
                    st.shared = True
                    st.lockset = held
                    st.shared_threads = {tid}
                    st.write_in_shared = is_write
                    if is_write and not held:
                        st.write_site = _caller_site()
                    return
                st.lockset &= held
                st.shared_threads.add(tid)
                if is_write:
                    st.write_in_shared = True
                    if not held:
                        st.write_site = _caller_site()
                if (
                    not st.lockset
                    and st.write_in_shared
                    and len(st.shared_threads) >= 2
                ):
                    st.reported = True
                    self.findings.append(
                        SharedStateFinding(
                            type(obj).__name__, attr,
                            st.write_site or _caller_site(),
                            len(st.shared_threads),
                        )
                    )
        finally:
            self._tls.busy = False

    def instrument_class(self, cls: type, attrs: Set[str]) -> None:
        """Wrap ``cls.__getattribute__``/``__setattr__`` so accesses to the
        named state attributes report into this monitor. Reversible via
        ``uninstrument_all``."""
        monitor = self
        watched = frozenset(attrs)
        orig_get = cls.__getattribute__
        orig_set = cls.__setattr__

        def tracked_getattribute(self, name):
            if name in watched:
                monitor.record(self, name, is_write=False)
            return orig_get(self, name)

        def tracked_setattr(self, name, value):
            if name in watched:
                monitor.record(self, name, is_write=True)
            orig_set(self, name, value)

        cls.__getattribute__ = tracked_getattribute  # type: ignore[assignment]
        cls.__setattr__ = tracked_setattr  # type: ignore[assignment]
        self._instrumented.append((cls, orig_get, orig_set))

    def uninstrument_all(self) -> None:
        while self._instrumented:
            cls, orig_get, orig_set = self._instrumented.pop()
            cls.__getattribute__ = orig_get  # type: ignore[assignment]
            cls.__setattr__ = orig_set  # type: ignore[assignment]


# ---------------------------------------------------------------------------
# findings allowlist (.racecheck-allow)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AllowRule:
    """One allowlist entry. ``kind`` selects the finding type
    (``shared-state`` matched against ``Class.attr``; ``lock-cycle``
    matched as a substring of any lock label in the cycle); ``reason`` is
    MANDATORY — an unexplained suppression is exactly the review smell
    this file exists to eliminate."""

    kind: str
    spec: str
    reason: str

    def matches(self, finding: Any) -> bool:
        if self.kind == "shared-state" and isinstance(finding, SharedStateFinding):
            return f"{finding.cls}.{finding.attr}" == self.spec
        if self.kind == "lock-cycle" and isinstance(finding, LockOrderFinding):
            return any(self.spec in label for label in finding.cycle)
        return False


def parse_allowlist(text: str, path: str = ALLOWLIST_FILENAME) -> List[AllowRule]:
    """The shared allowlist grammar (analysis.allowlist, same core
    storecheck rides): blank lines and ``#`` comments skipped; a rule
    without a reason, or with an unknown kind, is a hard error — the
    file's contract is that every deliberate pattern names WHY."""
    return allowlist.parse_rules(
        text, path, ("shared-state", "lock-cycle"), AllowRule
    )


def load_allowlist(path: str) -> List[AllowRule]:
    with open(path, encoding="utf-8") as f:
        return parse_allowlist(f.read(), path)


def find_allowlist(start_dir: str) -> Optional[str]:
    """Nearest .racecheck-allow walking up from ``start_dir`` (pytest
    rootdir resolution), never crossing the repository boundary — shared
    with storecheck via analysis.allowlist."""
    return allowlist.find_nearest(start_dir, ALLOWLIST_FILENAME)


# ---------------------------------------------------------------------------
# session
# ---------------------------------------------------------------------------

# control-plane classes instrumented by default (dotted path → state attrs).
# The attr sets name the underscore state each class guards (or should);
# they are the shared surfaces PRs 1-3 grew locks around.
DEFAULT_TARGETS: Dict[str, Tuple[str, ...]] = {
    "mpi_operator_tpu.machinery.workqueue:RateLimitingQueue": (
        "_queue", "_dirty", "_processing", "_failures", "_shutdown", "_timers",
    ),
    "mpi_operator_tpu.machinery.cache:Lister": ("_objects", "_index"),
    "mpi_operator_tpu.machinery.cache:InformerCache": ("_handlers",),
    "mpi_operator_tpu.machinery.store:ObjectStore": (
        "_objects", "_rv", "_watchers",
    ),
    "mpi_operator_tpu.machinery.http_store:_EventLog": (
        "_events", "_next_seq", "_base_rv", "_dropped_rv", "_max_rv",
    ),
    "mpi_operator_tpu.machinery.http_store:HttpStoreClient": (
        "_watchers", "_relist_listeners", "_cursor", "_max_rv", "_instance",
    ),
    "mpi_operator_tpu.executor.agent:StatusBatcher": ("_entries", "_committed"),
    "mpi_operator_tpu.controller.controller:TPUJobController": (
        "_ports_inflight",
    ),
}


class Session:
    """One racecheck window: installs the tracked lock factories (and the
    class instrumentation), collects, restores, reports. ``allowlist``
    entries (see :func:`load_allowlist`) suppress matching findings —
    the file-side channel for deliberate patterns, so they stop relying
    on code-side weakref/threshold exemptions alone."""

    def __init__(
        self,
        targets: Optional[Dict[str, Tuple[str, ...]]] = None,
        allowlist: Optional[List[AllowRule]] = None,
    ):
        self.tracker = LockTracker()
        self.monitor = SharedStateMonitor(self.tracker)
        self.targets = DEFAULT_TARGETS if targets is None else targets
        self.allowlist = list(allowlist or ())
        self.allowed: List[Tuple[Any, AllowRule]] = []
        self._installed = False

    def install(self) -> "Session":
        if self._installed:
            return self
        tracker = self.tracker
        threading.Lock = lambda: TrackedLock(tracker)  # type: ignore[assignment]
        threading.RLock = lambda: TrackedRLock(tracker)  # type: ignore[assignment]
        import importlib

        for dotted, attrs in self.targets.items():
            mod_name, _, cls_name = dotted.partition(":")
            try:
                cls = getattr(importlib.import_module(mod_name), cls_name)
            # oplint: disable=EXC001 — optional instrumentation target moved
            # or renamed: the detector degrades to fewer targets, not death
            except Exception:
                continue
            self.monitor.instrument_class(cls, set(attrs))
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = _REAL_LOCK  # type: ignore[assignment]
        threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
        self.monitor.uninstrument_all()
        self._installed = False

    def findings(self) -> List[Any]:
        """Findings surviving the allowlist; suppressed ones accumulate in
        ``self.allowed`` (reported informationally, never failing)."""
        out: List[Any] = []
        self.allowed = []
        for f in list(self.tracker.cycles()) + list(self.monitor.findings):
            rule = next((r for r in self.allowlist if r.matches(f)), None)
            if rule is not None:
                self.allowed.append((f, rule))
            else:
                out.append(f)
        return out

    def render_report(self) -> str:
        findings = self.findings()
        lines: List[str] = []
        if not findings:
            lines.append(
                f"racecheck: no lock-order cycles, no unguarded shared "
                f"writes ({len(self.tracker.labels)} locks tracked, "
                f"{len(self.tracker.edges)} order edges)"
            )
        else:
            lines.append(f"racecheck: {len(findings)} finding(s)")
            lines += ["  " + f.render().replace("\n", "\n  ") for f in findings]
        for f, rule in self.allowed:
            lines.append(
                f"  allowed ({rule.kind}:{rule.spec} — {rule.reason}): "
                + f.render().splitlines()[0]
            )
        return "\n".join(lines)


def self_test() -> List[str]:
    """Deterministic detector self-tests: a SEEDED lock-order cycle and a
    SEEDED unguarded shared write must both be caught, and the guarded
    idiom must stay silent. Returns a list of failures (empty = pass);
    the tier-1 meta-test and the CLI both ride this."""
    failures: List[str] = []

    # -- seeded lock-order cycle (A->B in one thread, B->A in another) ------
    # the threads run SEQUENTIALLY: the detector works on the acquisition
    # graph, so the inverted orders are a cycle even though this particular
    # schedule never deadlocks — exactly the point of lock-order checking
    sess = Session(targets={}).install()
    try:
        a, b = threading.Lock(), threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        for fn in (ab, ba):
            t = threading.Thread(target=fn)
            t.start()
            t.join(5.0)
        if not sess.tracker.cycles():
            failures.append("seeded lock-order cycle was NOT detected")
    finally:
        sess.uninstall()

    # -- clean ordering must stay silent ------------------------------------
    sess = Session(targets={}).install()
    try:
        a, b = threading.Lock(), threading.Lock()

        def nested():
            with a:
                with b:
                    pass

        threads = [threading.Thread(target=nested) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5.0)
        if sess.tracker.cycles():
            failures.append("consistent A->B ordering was falsely reported")
    finally:
        sess.uninstall()

    # -- seeded unguarded shared write --------------------------------------
    class _Racy:
        def __init__(self):
            self.counter = 0

    sess = Session(targets={}).install()
    try:
        sess.monitor.instrument_class(_Racy, {"counter"})
        guard = threading.Lock()
        obj = _Racy()

        def writer():
            for _ in range(3):
                obj.counter = obj.counter + 1  # no lock held

        t = threading.Thread(target=writer)
        t.start()
        t.join(5.0)
        with guard:
            _ = obj.counter  # main reads under a lock: no common lockset
        if not sess.monitor.findings:
            failures.append("seeded unguarded shared write was NOT detected")
    finally:
        sess.uninstall()

    # -- properly guarded state must stay silent -----------------------------
    class _Guarded:
        def __init__(self):
            self.lock = threading.Lock()
            self.counter = 0

    sess = Session(targets={}).install()
    try:
        sess.monitor.instrument_class(_Guarded, {"counter"})
        obj = _Guarded()

        def bump():
            for _ in range(3):
                with obj.lock:
                    obj.counter += 1

        threads = [threading.Thread(target=bump) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5.0)
        with obj.lock:
            _ = obj.counter
        if sess.monitor.findings:
            failures.append(
                "lock-guarded counter was falsely reported: "
                + sess.monitor.findings[0].render()
            )
    finally:
        sess.uninstall()

    return failures
