"""crashpoints: ALICE-style crash-point exploration of the SqliteStore.

PR 3's chaos e2e proves crash-recovery for ONE scripted SIGKILL schedule;
durability of every other crash point was an argument, not a test. This
module enumerates them, after ALICE (Pillai et al., OSDI'14): run a
commit-heavy workload against a real ``SqliteStore``, and at every
transaction-boundary announcement of the sanctioned ``_txn`` helper
(``sqlite.txn`` before the transaction, ``sqlite.commit`` after the commit
lands — the os-write/commit seam, announced through
``machinery.yieldpoints`` like every other store op) snapshot the db and
WAL file BYTES. Each snapshot is a state a crash could strand on disk;
each is reopened by a fresh ``SqliteStore`` and checked against the
sequential model's timeline:

- **acked-write durability**: an exact snapshot recovers to EXACTLY the
  model state at its commit count — every acked write present at its
  exact rv, nothing else (no phantom objects, no partial transactions);
- **rv monotonicity across reopen**: the recovered rv high-water matches
  the model's, and a probe write after reopen lands strictly above it;
- **the resume contract**: a ``?resource_version=`` watch (re)registration
  against a server over the recovered store is either a provably-complete
  tail or a clean relist (the 410 Gone fallback) — never a silent gap.

**Torn tails** are the second half of the model: ``synchronous=NORMAL``
(the store's documented stance) does not fsync the WAL per commit, so an
OS/power crash may lose the newest commits. Each commit snapshot also
spawns variants with the WAL tail truncated at several byte offsets; those
must recover to a committed PREFIX of the timeline (sqlite discards torn
frames — corruption or invented state is always a failure), and a prefix
that drops an *acked* write is the gated ``crash:torn-tail`` exception:
allowed only when the repo's ``.storecheck-allow`` names it with a reason.

The explorer's own acceptance gate (:func:`self_test`): a seeded mutant
store that splits one logical create across TWO transactions (the
atomicity bug the ``_txn`` helper + oplint DUR001 exist to prevent) MUST
be caught — a crash between its commits strands an rv with no object —
while the real store explores ≥ 50 points clean.
"""

from __future__ import annotations

import copy
import os
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from mpi_operator_tpu.analysis.model import ModelStore
from mpi_operator_tpu.analysis import storecheck
from mpi_operator_tpu.machinery import yieldpoints
from mpi_operator_tpu.machinery.serialize import decode, encode

_SEAMS = ("sqlite.txn", "sqlite.commit")

# deterministic WAL truncation offsets per commit snapshot: 1 byte (tear
# the final frame's checksum), 37 bytes (tear into the final page image),
# and half the WAL (lose a swath of commits)
_TORN_CUTS = (1, 37)


class CrashExploreError(RuntimeError):
    """The explorer machinery itself failed (workload diverged from the
    model, snapshot unreadable) — distinct from a Violation, a finding."""


# ---------------------------------------------------------------------------
# workload (deterministic, all-successful, commit-heavy)
# ---------------------------------------------------------------------------


def commit_heavy_ops(writes: int = 16) -> List[Dict[str, Any]]:
    """A deterministic create→patch-status→update→delete round-robin over
    a small name pool: every op commits (no expected errors), deletes are
    followed by same-name recreates on the next round, and status patches
    ride the subresource — the exact write mix the operator's hot path
    produces. Symbolic storecheck ops, so resolution/execution reuse the
    fuzzer's machinery."""
    names = ("a", "b")
    ops: List[Dict[str, Any]] = []
    i = 0
    while len(ops) < writes:
        name = names[(i // 4) % len(names)]
        cycle = i % 4
        if cycle == 0:
            ops.append({"op": "create", "kind": "Pod", "name": name,
                        "uid": f"cp{i}", "labels": {"job": "j1"}})
        elif cycle == 1:
            ops.append({"op": "patch", "kind": "Pod", "name": name,
                        "rv": None, "uid": "current",
                        "subresource": "status",
                        "body": {"status": {"phase": "Running"}}})
        elif cycle == 2:
            ops.append({"op": "update", "kind": "Pod", "name": name,
                        "rv": "current", "force": False,
                        "label": ["round", str(i)]})
        else:
            ops.append({"op": "delete", "kind": "Pod", "name": name})
        i += 1
    return ops


# ---------------------------------------------------------------------------
# recording pass: snapshot the file bytes at every announced seam point
# ---------------------------------------------------------------------------


@dataclass
class _Snapshot:
    label: str
    seam: str  # sqlite.txn | sqlite.commit
    acked: int  # workload ops returned when the point fired
    expected: int  # timeline index an EXACT recovery must equal
    db: bytes
    wal: bytes


@dataclass
class CrashPoint:
    label: str
    acked: int
    expected: int
    torn: int  # 0 = exact snapshot; >0 = bytes cut off the WAL tail
    db: bytes
    wal: bytes


@dataclass
class Violation:
    point: str
    message: str

    def render(self) -> str:
        return f"{self.point}: {self.message}"


@dataclass
class CrashReport:
    ok: bool
    points: int
    exact_points: int
    torn_points: int
    violations: List[Violation]
    # torn-tail acked losses gated by the allowlist: (point label, reason)
    allowed: List[Tuple[str, str]] = field(default_factory=list)

    def render(self) -> str:
        head = (
            f"crashpoints: {self.points} crash point(s) "
            f"({self.exact_points} exact, {self.torn_points} torn-tail)"
        )
        if self.ok:
            lines = [head + " — every one recovers within the contract"]
        else:
            lines = [head + f" — {len(self.violations)} VIOLATION(S)"]
            lines += ["  " + v.render() for v in self.violations]
        for label, reason in self.allowed:
            lines.append(
                f"  allowed (crash:torn-tail): {label} — {reason}"
            )
        return "\n".join(lines)


class _Hook:
    """yieldpoints hook for the recording pass: on every ``sqlite.txn`` /
    ``sqlite.commit`` announcement, capture the db+WAL bytes plus the
    workload progress (how many ops have been acked, and which timeline
    state an exact recovery must therefore equal)."""

    def __init__(self, db_path: str):
        self.db_path = db_path
        self.acked = 0
        self.snaps: List[_Snapshot] = []
        self._seq = 0

    def __call__(self, op: str, detail: str) -> None:
        if op not in _SEAMS:
            return
        self._seq += 1
        self.snaps.append(_Snapshot(
            label=f"{op.split('.')[1]}@{self._seq}:{detail}",
            seam=op,
            acked=self.acked,
            # pre-transaction: commits 0..acked-1 are on disk; post-commit:
            # the in-flight op (index ``acked``) has landed too
            expected=self.acked if op == "sqlite.txn" else self.acked + 1,
            db=_read(self.db_path),
            wal=_read(self.db_path + "-wal"),
        ))


def _read(path: str) -> bytes:
    try:
        with open(path, "rb") as f:
            return f.read()
    except FileNotFoundError:
        return b""


def record(
    ops: List[Dict[str, Any]],
    *,
    store_cls=None,
) -> Tuple[List[_Snapshot], List[Dict[Tuple[str, str, str], Dict[str, Any]]],
           List[int]]:
    """Run the workload against a fresh store of ``store_cls`` (default
    SqliteStore) in lockstep with the model, snapshotting at every seam
    announcement. Returns (snapshots, state timeline, rv timeline) where
    ``timeline[i]`` is the model state after i committed ops."""
    from mpi_operator_tpu.machinery.sqlite_store import SqliteStore

    store_cls = store_cls or SqliteStore
    model = ModelStore()
    timeline = [copy.deepcopy(model.snapshot())]
    rvs = [0]
    d = tempfile.mkdtemp(prefix="crashpoints-")
    db_path = os.path.join(d, "store.db")
    hook = _Hook(db_path)
    prev = yieldpoints.set_hook(None)  # the store's __init__ writes too,
    try:                               # but timeline[0] only exists after
        store = store_cls(db_path)     # the schema lands: hook goes in now
        yieldpoints.set_hook(hook)
        h = storecheck.Harness("sqlite-crash", store)
        for op in ops:
            c = storecheck.resolve(op, model)
            want = storecheck._exec_model(model, c)
            got = storecheck._exec_backend(h, c)
            if want != got:
                raise CrashExploreError(
                    f"workload diverged from the model at {op!r}: "
                    f"{want!r} != {got!r} (fix the workload or run the "
                    f"differential fuzzer)"
                )
            hook.acked += 1
            timeline.append(copy.deepcopy(model.snapshot()))
            rvs.append(model.current_rv())
        yieldpoints.set_hook(None)
        store.close()
        return hook.snaps, timeline, rvs
    finally:
        yieldpoints.set_hook(prev)
        shutil.rmtree(d, ignore_errors=True)


def crash_points(
    snaps: List[_Snapshot], *, torn: bool = True
) -> List[CrashPoint]:
    """Expand snapshots into crash points: each exact snapshot, plus —
    for commit-seam snapshots with a WAL tail to tear — truncated-tail
    variants (the synchronous=NORMAL power-crash model)."""
    points: List[CrashPoint] = []
    for s in snaps:
        points.append(CrashPoint(s.label, s.acked, s.expected, 0, s.db,
                                 s.wal))
        if not torn or s.seam != "sqlite.commit":
            continue
        cuts = list(_TORN_CUTS) + [len(s.wal) // 2]
        for cut in sorted({c for c in cuts if 0 < c < len(s.wal)}):
            points.append(CrashPoint(
                f"{s.label}:torn-{cut}", s.acked, s.expected, cut,
                s.db, s.wal[:-cut],
            ))
    return points


# ---------------------------------------------------------------------------
# recovery checking
# ---------------------------------------------------------------------------


def _recovered_state(store) -> Dict[Tuple[str, str, str], Dict[str, Any]]:
    out: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
    for kind in ("Pod", "TPUJob", "Node"):
        for obj in store.list(kind):
            m = obj.metadata
            out[(kind, m.namespace, m.name)] = encode(obj)
    return out


def _check_resume_contract(store, anchor: int,
                           state) -> Optional[str]:
    """A ?resource_version= (re)registration against a server over the
    recovered store must come back as a provably-complete tail (a fresh
    incarnation can only prove the empty tail at its own base) or a clean
    relist matching the recovered state — anything else is a silent gap."""
    from mpi_operator_tpu.machinery.http_store import StoreServer

    srv = StoreServer(store, "127.0.0.1", 0).start()
    try:
        payload = storecheck.probe_resume(srv.url, anchor)
    finally:
        srv.stop()
    if "relist" in payload:
        got = sorted(
            (o.get("kind"), (o.get("metadata") or {}).get("name"),
             (o.get("metadata") or {}).get("resource_version"))
            for o in payload["relist"]
        )
        want = sorted(
            (k, name, (o.get("metadata") or {}).get("resource_version"))
            for (k, _ns, name), o in state.items()
        )
        if got != want:
            return f"relist does not match recovered state: {got} != {want}"
        return None
    events = payload.get("events")
    if events == []:
        # a fresh incarnation proves completeness only at its own base rv:
        # an empty tail asserts the client missed nothing
        base = max(
            [(o.get("metadata") or {}).get("resource_version", 0)
             for o in state.values()] or [0]
        )
        if anchor < base:
            return (f"empty resume at anchor {anchor} below recovered "
                    f"base {base}: silently skipped events")
        return None
    return f"resume returned a non-empty tail from a fresh incarnation: " \
           f"{events!r}"


def check_point(
    pt: CrashPoint,
    timeline,
    rvs: List[int],
    *,
    resume: bool = True,
) -> Tuple[Optional[Violation], bool]:
    """Reopen one crash state and check the recovery invariants. Returns
    (violation, torn_acked_loss): the second is True when a torn-tail
    point recovered to a prefix that drops an ACKED write — legal only
    through the ``crash:torn-tail`` allowlist gate."""
    from mpi_operator_tpu.machinery.sqlite_store import SqliteStore

    d = tempfile.mkdtemp(prefix="crashpoint-")
    try:
        db_path = os.path.join(d, "store.db")
        with open(db_path, "wb") as f:
            f.write(pt.db)
        if pt.wal:
            with open(db_path + "-wal", "wb") as f:
                f.write(pt.wal)
        try:
            store = SqliteStore(db_path)
        # oplint: disable=EXC001 — not swallowed: ANY open failure on a
        # crash-state snapshot (sqlite3.DatabaseError, torn-header
        # ValueError, ...) is converted into a reported Violation, the
        # explorer's strongest possible signal
        except Exception as e:
            return Violation(
                pt.label, f"recovered store failed to OPEN: "
                          f"{type(e).__name__}: {e}"
            ), False
        try:
            state = _recovered_state(store)
            rv = store.current_rv()
            if pt.torn == 0:
                j = pt.expected
                if state != timeline[j] or rv != rvs[j]:
                    return Violation(
                        pt.label,
                        f"exact snapshot must recover to timeline[{j}] "
                        f"(rv {rvs[j]}): got rv {rv}, state "
                        f"{sorted(state)} vs {sorted(timeline[j])} — an "
                        f"acked write is missing, partial, or phantom",
                    ), False
            else:
                j = next(
                    (k for k in range(pt.expected, -1, -1)
                     if timeline[k] == state and rvs[k] == rv),
                    None,
                )
                if j is None:
                    return Violation(
                        pt.label,
                        f"torn tail recovered to a state matching NO "
                        f"committed prefix (rv {rv}): invented or "
                        f"corrupt state",
                    ), False
            # rv monotonicity across reopen: a probe write lands strictly
            # above the recovered high-water mark
            probe = store.create(decode("Pod", {
                "kind": "Pod",
                "metadata": {"name": "crash-probe", "namespace": "default",
                             "uid": "u-probe",
                             "creation_timestamp": 1000.0},
            }))
            if probe.metadata.resource_version <= rv:
                return Violation(
                    pt.label,
                    f"rv NOT monotone across reopen: probe write got rv "
                    f"{probe.metadata.resource_version} <= recovered {rv}",
                ), False
            store.delete("Pod", "default", "crash-probe")
            if resume:
                # re-anchor at the last rv the workload had ACKED when the
                # crash hit — the position a surviving watcher would resume
                # from
                state2 = _recovered_state(store)
                err = _check_resume_contract(store, rvs[pt.acked], state2)
                if err is not None:
                    return Violation(pt.label, err), False
            return None, pt.torn > 0 and j < pt.acked
        finally:
            store.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)


def explore(
    *,
    writes: int = 16,
    torn: bool = True,
    resume: bool = True,
    allowlist: Optional[List["storecheck.AllowRule"]] = None,
    store_cls=None,
) -> CrashReport:
    """The full pass: record the workload, expand crash points, check
    every one. Torn-tail acked losses are failures unless a
    ``crash:torn-tail`` allowlist rule gates them (reported
    informationally, racecheck-allow style)."""
    snaps, timeline, rvs = record(commit_heavy_ops(writes),
                                  store_cls=store_cls)
    points = crash_points(snaps, torn=torn)
    violations: List[Violation] = []
    allowed: List[Tuple[str, str]] = []
    gate = next(
        (r for r in (allowlist or [])
         if r.kind == "crash" and r.spec == "torn-tail"),
        None,
    )
    for pt in points:
        v, torn_loss = check_point(pt, timeline, rvs, resume=resume)
        if v is not None:
            violations.append(v)
        elif torn_loss:
            if gate is not None:
                allowed.append((pt.label, gate.reason))
            else:
                violations.append(Violation(
                    pt.label,
                    "torn tail dropped an ACKED write (synchronous=NORMAL "
                    "power-crash window); gate it with a reasoned "
                    "`crash:torn-tail` entry in .storecheck-allow or run "
                    "with synchronous=FULL",
                ))
    exact = sum(1 for p in points if p.torn == 0)
    return CrashReport(
        ok=not violations,
        points=len(points),
        exact_points=exact,
        torn_points=len(points) - exact,
        violations=violations,
        allowed=allowed,
    )


# ---------------------------------------------------------------------------
# kill-during-log-ship: crash points of a replica-set LEADER (ISSUE 8)
# ---------------------------------------------------------------------------


@dataclass
class _ReplicaPoint:
    """One leader-crash state: all three nodes' file bytes captured at a
    single ``sqlite.txn``/``sqlite.commit`` announcement. Leader-side
    announcements fire BEFORE any ship (followers hold exactly the acked
    prefix); follower-side ``replicate`` announcements fire mid-ship (the
    in-flight entry is on some but maybe not all followers) — together
    they enumerate every phase a leader SIGKILL can strand the set in."""

    label: str
    acked: int
    files: Dict[str, Tuple[bytes, bytes]]  # node id -> (db, wal) bytes


class _ReplicaHook:
    def __init__(self, paths: Dict[str, str]):
        self.paths = paths
        self.acked = 0
        self.points: List[_ReplicaPoint] = []
        self._seq = 0

    def __call__(self, op: str, detail: str) -> None:
        if op not in _SEAMS:
            return
        self._seq += 1
        self.points.append(_ReplicaPoint(
            label=f"replica:{op.split('.')[1]}@{self._seq}:{detail}",
            acked=self.acked,
            files={
                nid: (_read(p), _read(p + "-wal"))
                for nid, p in self.paths.items()
            },
        ))


def record_replica(
    ops: List[Dict[str, Any]],
) -> Tuple[List[_ReplicaPoint], List[Dict], List[int]]:
    """Run the commit-heavy workload against a real 3-node replica set
    (leader n0, reads from follower n1) in lockstep with the model,
    snapshotting every node's file bytes at every sqlite seam
    announcement — leader commits and follower applies both announce, so
    the capture covers pre-ship, mid-ship and post-ship instants."""
    from mpi_operator_tpu.machinery.replicated_store import ReplicaSet

    model = ModelStore()
    timeline = [copy.deepcopy(model.snapshot())]
    rvs = [0]
    d = tempfile.mkdtemp(prefix="crashpoints-replica-")
    rset = ReplicaSet(3, dir=d)
    prev = yieldpoints.set_hook(None)
    try:  # rset.stop() rides the finally: a mid-workload divergence must
        # not leak three sqlite handles + poller threads per call
        if not rset.elect("n0"):
            raise CrashExploreError("fresh replica set failed its election")
        client = rset.client(read_from="n1")
        hook = _ReplicaHook(
            {nid: rset.nodes[nid].path for nid in rset.node_ids}
        )
        yieldpoints.set_hook(hook)
        h = storecheck.Harness("replica-crash", client)
        for op in ops:
            c = storecheck.resolve(op, model)
            want = storecheck._exec_model(model, c)
            got = storecheck._exec_backend(h, c)
            if want != got:
                raise CrashExploreError(
                    f"replica workload diverged from the model at {op!r}: "
                    f"{want!r} != {got!r} (run the differential fuzzer)"
                )
            hook.acked += 1
            timeline.append(copy.deepcopy(model.snapshot()))
            rvs.append(model.current_rv())
        return hook.points, timeline, rvs
    finally:
        # unhook BEFORE stop(): node close()s announce through the same
        # seam and must not record phantom points (or leak into an outer
        # hook restored too early)
        yieldpoints.set_hook(None)
        rset.stop()
        yieldpoints.set_hook(prev)
        shutil.rmtree(d, ignore_errors=True)


def check_replica_point(pt: _ReplicaPoint, timeline,
                        rvs: List[int]) -> Optional[Violation]:
    """SIGKILL the leader at this instant and recover: reopen BOTH
    followers from their captured bytes, elect among them, and assert

    - the surviving quorum recovers to timeline[j] for j in
      {acked, acked+1} at exactly rvs[j] — every ACKED write present
      (j < acked is a lost ack), the in-flight op present only as a
      whole committed entry (indeterminate, never partial);
    - rv stays monotone across the failover (a probe write through the
      new leader lands strictly above);
    - the ex-leader rejoining from ITS bytes converges to the new
      history — its locally-committed-but-unacked suffix is truncated,
      never resurrected."""
    from mpi_operator_tpu.machinery.replicated_store import ReplicaSet

    d = tempfile.mkdtemp(prefix="crashpoint-replica-")
    try:
        for nid, (db, wal) in pt.files.items():
            with open(os.path.join(d, f"{nid}.db"), "wb") as f:
                f.write(db)
            if wal:
                with open(os.path.join(d, f"{nid}.db-wal"), "wb") as f:
                    f.write(wal)
        rset = ReplicaSet(3, dir=d)
        try:
            rset.crash("n0")  # the SIGKILLed leader stays dead for now
            rset.expire_leases()
            if not rset.elect("n1"):
                return Violation(
                    pt.label,
                    "surviving majority could not elect a leader",
                )
            lead = rset.nodes["n1"]
            state = _recovered_state(lead)
            rv = lead.current_rv()
            j = next(
                (k for k in (pt.acked + 1, pt.acked)
                 if k < len(timeline) and timeline[k] == state
                 and rvs[k] == rv),
                None,
            )
            if j is None:
                lost = next(
                    (k for k in range(pt.acked - 1, -1, -1)
                     if timeline[k] == state), None,
                )
                what = (f"an ACKED write was lost (recovered to "
                        f"timeline[{lost}] < acked {pt.acked})"
                        if lost is not None else
                        "invented or partial state")
                return Violation(
                    pt.label,
                    f"survivors recovered to rv {rv}, matching neither "
                    f"timeline[{pt.acked}] nor [{pt.acked + 1}]: {what}",
                )
            probe = lead.create(decode("Pod", {
                "kind": "Pod",
                "metadata": {"name": "crash-probe", "namespace": "default",
                             "uid": "u-probe",
                             "creation_timestamp": 1000.0},
            }))
            if probe.metadata.resource_version <= rv:
                return Violation(
                    pt.label,
                    f"rv NOT monotone across failover: probe got rv "
                    f"{probe.metadata.resource_version} <= recovered {rv}",
                )
            lead.delete("Pod", "default", "crash-probe")
            # the ex-leader rejoins from its own crash-state bytes: its
            # unacked suffix (if the quorum settled on j == acked) must
            # truncate via resync, and all three histories converge
            rset.restart("n0")
            lead.renew()
            ex = rset.nodes["n0"]
            if (_recovered_state(ex) != _recovered_state(lead)
                    or ex.current_rv() != lead.current_rv()):
                return Violation(
                    pt.label,
                    f"rejoined ex-leader diverges from the new history "
                    f"(rv {ex.current_rv()} vs {lead.current_rv()}): "
                    f"unacked suffix resurrected or resync failed",
                )
            return None
        finally:
            rset.stop()
    finally:
        shutil.rmtree(d, ignore_errors=True)


def explore_replica(*, writes: int = 8) -> CrashReport:
    """The kill-during-log-ship pass: record the replicated workload,
    then SIGKILL-the-leader at every captured instant and check the
    failover recovery contract (no torn variants — the follower copies,
    not the leader's WAL tail, are the durability story here)."""
    points, timeline, rvs = record_replica(commit_heavy_ops(writes))
    violations: List[Violation] = []
    for pt in points:
        v = check_replica_point(pt, timeline, rvs)
        if v is not None:
            violations.append(v)
    return CrashReport(
        ok=not violations,
        points=len(points),
        exact_points=len(points),
        torn_points=0,
        violations=violations,
    )


# ---------------------------------------------------------------------------
# the seeded atomicity mutant (the explorer's own acceptance proof)
# ---------------------------------------------------------------------------


def split_txn_store_cls():
    """A SqliteStore whose ``create`` commits the log row and the objects
    row in SEPARATE transactions — exactly the bug class the sanctioned
    ``_txn`` helper and oplint DUR001 exist to prevent. A crash between
    the two commits strands an allocated rv with no object behind it; the
    explorer's exact-snapshot check MUST flag it."""
    from mpi_operator_tpu.machinery.sqlite_store import SqliteStore

    class SplitTxnSqliteStore(SqliteStore):
        def create(self, obj):
            import time as _time
            import uuid as _uuid

            obj = obj.deepcopy()
            m = obj.metadata
            with self._txn("create-log") as cur:
                row = cur.execute(
                    "SELECT 1 FROM objects WHERE kind=? AND namespace=? "
                    "AND name=?",
                    (obj.kind, m.namespace, m.name),
                ).fetchone()
                if row is not None:
                    from mpi_operator_tpu.machinery.store import (
                        AlreadyExists,
                    )

                    raise AlreadyExists(
                        f"{obj.kind} {m.namespace}/{m.name} already exists"
                    )
                if not m.uid:
                    m.uid = str(_uuid.uuid4())
                if m.creation_timestamp is None:
                    m.creation_timestamp = _time.time()
                rv = self._log(cur, "ADDED", obj)
                m.resource_version = rv
                cur.execute(
                    "UPDATE log SET data=? WHERE rv=?", (self._dump(obj), rv)
                )
            # the crash window: the log row (and its rv) is committed,
            # the object is not
            with self._txn("create-object") as cur:
                cur.execute(
                    "INSERT INTO objects (kind, namespace, name, rv, data) "
                    "VALUES (?, ?, ?, ?, ?)",
                    (obj.kind, m.namespace, m.name, rv, self._dump(obj)),
                )
            return obj.deepcopy()

    return SplitTxnSqliteStore


def self_test(writes: int = 16) -> List[str]:
    """The explorer's acceptance gate: the real store explores >= 50
    crash points with zero violations (torn acked losses gated), and the
    seeded split-transaction mutant is caught."""
    failures: List[str] = []
    gate = [storecheck.AllowRule(
        "crash", "torn-tail", "selftest: the documented "
        "synchronous=NORMAL stance"
    )]
    report = explore(writes=writes, allowlist=gate)
    if not report.ok:
        failures.append(
            "real SqliteStore must recover every crash point: "
            + report.render()
        )
    if report.points < 50:
        failures.append(
            f"only {report.points} crash points enumerated (< 50); "
            f"raise --writes"
        )
    # resume=False: the seeded atomicity bug is caught by the
    # exact-snapshot state check; per-point servers would only add time
    seeded = explore(writes=8, allowlist=gate, resume=False,
                     store_cls=split_txn_store_cls())
    if seeded.ok:
        failures.append(
            "seeded split-transaction mutant was NOT caught: a crash "
            "between its two commits must strand an rv with no object"
        )
    return failures
