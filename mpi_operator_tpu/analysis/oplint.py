"""oplint: an AST rule engine over this repo's own control-plane idioms.

≙ the reference's golangci-lint gate (.github/workflows/golangci-lint.yml):
the invariants PRs 1-3 fought for — patch-with-rv instead of GET+PUT
read-modify-write, uid-pinned status writes, terminal write-once,
stop-observing loops — lived only in reviewers' heads and in after-the-fact
chaos tests. Each rule here is mined from a real past bug and catches the
regression at diff time, not at chaos-replay time.

Rule catalog (rationale → the PR that motivated each):

- **RMW001** raw store ``get``+``update`` read-modify-write in one function.
  PR 2 replaced every GET+PUT+409-retry loop with one server-side
  merge-patch carrying an rv precondition; a new GET+PUT loop reintroduces
  the clobber race AND the double round-trip. Blessed forms: ``.patch`` with
  a precondition, or the ``optimistic_update`` helper.
- **UID001** Pod/TPUJob status-subresource patch without a uid/rv pin.
  PR 3's chaos suite proved a stale reconcile can cross-stamp a recreated
  same-name object (pre-burning its backoffLimit); every status write on an
  incarnation-sensitive kind must pin ``metadata.uid`` or ride an rv
  precondition. Node heartbeats are exempt — their merge is incarnation-free
  by design.
- **TERM001** writes that can resurrect a terminal phase: a force-PUT
  (``update(..., force=True)``), or assigning ``.status.phase`` and PUTing
  the object back. PR 2 made terminal pod status write-once (the Evicted
  marker must survive the reaper of the process the eviction killed);
  the blessed path is ``patch_pod_status``/``evict_pod``.
- **BLK001** blocking calls that cannot observe shutdown inside
  reconcile/watch/handler loops: unbounded ``queue.get()``, un-timeouted
  ``urlopen``/``create_connection``/``settimeout(None)``, ``time.sleep`` in
  a run/sync/pump/handler loop body (use ``Event.wait``). PR 3's chaos
  scenarios hang exactly here when a stop event cannot be observed.
- **EXC001** bare ``except:`` anywhere, and broad ``except Exception``
  whose handler neither logs nor re-raises in controller/agent loop code —
  a swallowed fault in a reconcile loop is invisible until a chaos replay.
- **SEC001** token/secret values interpolated into log output or URLs.
  PR 3's VERDICT found ``ctl logs`` shipping the admin bearer token over
  plain HTTP; secrets may be *presented* (Authorization headers) but never
  *printed* or baked into a URL.
- **DUR001** a direct sqlite mutation — write-SQL ``execute``,
  ``executescript``, ``commit()``, or a ``with conn:`` transaction block —
  on a store connection outside the sanctioned ``_txn`` helper. ISSUE 6's
  crash-point explorer (analysis/crashpoints.py) interposes on the
  ``sqlite.txn``/``sqlite.commit`` seam that helper announces through; a
  mutation that bypasses it is invisible to the explorer AND can split one
  logical write across transactions — a crash between them strands an rv
  with no object (the seeded mutant crashpoints.self_test proves is
  caught). Read-only ``execute`` (SELECT, PRAGMA queries) is fine.
- **LCK001** a blocking store/HTTP call made while holding a lock
  (AST-approximated: a ``with self._lock:`` body containing
  ``store.get/update/patch/list/...`` or ``urlopen``/``_request``).
  ISSUE 5's explorer work surfaced two live instances: the http client's
  watch bootstrap held ``self._lock`` across a network round-trip
  (stalling stop_watch and the poll loop's fan-out snapshot behind the
  request timeout), and the gang scheduler listed pods under the
  scheduler lock. A lock held across a round-trip turns one slow backend
  response into a control-plane-wide stall.
- **DIS001** a teardown verb (``evict_pod``, a direct Pod delete) inside a
  drain/maintenance/migration-named code path outside the DrainController's
  sanctioned seam. ISSUE 14 made planned disruption budgeted (serve
  DisruptionBudget floors, maintenance evictions that never burn
  backoffLimit, one-eviction dedupe against the node monitor); an ad-hoc
  eviction on a drain path silently forfeits all three. The seam:
  ``_migrate_batch_gangs``/``_escalate`` (controller/disruption.py),
  the serve controller's ``_drain_replica`` retire primitive, and the
  rescheduler's ``_migrate_gang`` whole-gang free migration
  (controller/rescheduler.py, ISSUE 18).
- **REP001** a mutation verb invoked directly on a follower/standby
  handle (``follower.update(...)``, ``self.standby.store.delete(...)``).
  ISSUE 8's replicated store routes every write through the leased
  leader; a direct follower write forks the replicated history in a way
  no election can reconcile (the divergence-hash resync would silently
  truncate it — or worse, ship it). The sanctioned follower write path
  is the replication apply seam (``apply_replicated``/``install_snapshot``
  /``append_entries``/``load_snapshot``), which the checker exempts by
  enclosing-function name.
- **CKP001** a blocking checkpoint-commit wait (``mgr.wait()``,
  ``manager.wait_until_finished()``) reached from step-loop code
  (train/elastic/step-loop-named functions) outside the sanctioned seams.
  ISSUE 16 made periodic saves async — the disk commit overlaps the next
  steps and the ``ckpt`` stall bucket charges only the blocking snapshot
  slice; a wait inside the step loop re-serializes every save and
  resurrects the periodic goodput spike the async path removed. The
  sanctioned blocking seams: the force-checkpoint/terminal-exit helper
  (``_final_checkpoint``, ops/elastic.py), the pre-restore fence
  (``restore``), and teardown (``close``).
- **OBS004** a ``train_stats``/``serve_stats`` status blob constructed
  outside the bounded-blob helpers (``bounded_train_stats``/
  ``bounded_serve_stats``, machinery/objects.py). ISSUE 15: status blobs
  ride every watch event delivering the pod — an unbounded dict there is
  a watch-fan-out size multiplier. Blessed shapes: a direct helper call,
  a name assigned from one in the same/enclosing scope, or ``None``
  (clearing).

Suppression: ``# oplint: disable=RULE[,RULE...]`` on the flagged line or the
line directly above it silences that rule there. Policy: every suppression
carries a reason in the same comment block — a bare disable is a review
smell (README "Static analysis & race checking").
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# rule API
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Rule:
    """One lint rule. ``scope`` is 'src' (package code only) or 'all'
    (tests too) — test code legitimately pokes raw store verbs and swallows
    exceptions in teardown, so most control-plane rules stay out of it.
    ``autofixable`` is metadata for a future --fix mode (none of the first
    ruleset is mechanically fixable without judgment)."""

    id: str
    severity: str  # "error" | "warning"
    summary: str
    rationale: str
    scope: str = "src"
    autofixable: bool = False


@dataclass(frozen=True)
class Finding:
    rule_id: str
    severity: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}"

    def to_dict(self) -> Dict[str, object]:
        """The STABLE machine-readable schema (``lint --format json``):
        exactly these six keys, so CI diff-annotators can parse findings
        without tracking internal field names. Renames here are breaking —
        the CLI contract test pins the shape."""
        return {
            "rule": self.rule_id,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


RULES: Dict[str, Rule] = {
    r.id: r
    for r in (
        Rule(
            "RMW001", "error",
            "raw store get+update read-modify-write",
            "PR 2: every GET+PUT+409 loop became one merge-patch with an rv "
            "precondition; use .patch or optimistic_update",
        ),
        Rule(
            "UID001", "error",
            "Pod/TPUJob status write without a uid/rv pin",
            "PR 3: a stale reconcile must never cross-stamp a recreated "
            "same-name incarnation",
        ),
        Rule(
            "TERM001", "error",
            "write can resurrect a terminal phase",
            "PR 2: terminal pod status is write-once (the Evicted marker "
            "survives the reaper); use patch_pod_status/evict_pod",
        ),
        Rule(
            "BLK001", "error",
            "blocking call cannot observe shutdown",
            "PR 3: chaos scenarios hang in loops that cannot see the stop "
            "event; bound every wait",
        ),
        Rule(
            "EXC001", "warning",
            "swallowed broad exception in loop code",
            "a fault swallowed in a reconcile/agent loop is invisible until "
            "a chaos replay; log it, narrow it, or annotate why not",
        ),
        Rule(
            "SEC001", "error",
            "secret value reaches a log line or URL",
            "PR 3 VERDICT: the admin bearer token crossed plain HTTP; "
            "secrets are presented in headers, never printed or URL-baked",
            scope="all",
        ),
        Rule(
            "DUR001", "error",
            "sqlite mutation bypasses the sanctioned transaction helper",
            "ISSUE 6: the ALICE crash-point explorer interposes on the "
            "_txn seam; a mutation outside it is invisible to crash "
            "exploration and can split one logical write across "
            "transactions — a crash between them strands an rv with no "
            "object behind it",
        ),
        Rule(
            "LCK001", "error",
            "blocking store/HTTP call while holding a lock",
            "ISSUE 5: the http watch bootstrap and the gang scheduler's "
            "accounting both held a lock across a store round-trip — one "
            "slow response stalls every contender; move the call outside "
            "or annotate why the lock is uncontended",
        ),
        Rule(
            "OBS001", "error",
            "bare start_span() outside a with statement",
            "ISSUE 9: a span opened without the context-manager form stays "
            "on the thread's span stack when the exception path skips its "
            "finish() — every later span silently re-parents under the "
            "leaked one and the causal timeline lies; use "
            "`with start_span(...)`",
        ),
        Rule(
            "OBS002", "error",
            "controller-loop span without a latency histogram observation",
            "ISSUE 11: span-close sites ARE the histogram instrumentation "
            "points — a reconcile/sync loop that opens its span but never "
            "observes a histogram has latency PERF.md and the SLO "
            "tripwires cannot see; observe a metrics histogram in the "
            "same function the loop span closes in",
        ),
        Rule(
            "OBS003", "error",
            "metric registered without HELP, or SLO objective on an "
            "unknown metric family",
            "ISSUE 13: the SLO monitor validates objectives against the "
            "registry catalog and `ctl top`/dashboards render HELP text — "
            "a counter/gauge/histogram registered with empty HELP is "
            "unreadable at triage time, and an Objective(...) naming a "
            "family the registry never registers would silently watch "
            "nothing (the config loader fails closed at runtime; this "
            "catches it at diff time)",
            scope="all",
        ),
        Rule(
            "OBS004", "error",
            "train_stats/serve_stats status blob built outside the "
            "bounded-blob helper",
            "ISSUE 15: pod status blobs ride EVERY watch event delivering "
            "the pod, so their size is a fan-out multiplier — an "
            "unbounded dict mirrored into status.train_stats/serve_stats "
            "bloats the whole control plane's watch traffic; construct "
            "the blob with bounded_train_stats/bounded_serve_stats "
            "(machinery/objects.py), which clamp keys and round values "
            "at the source",
        ),
        Rule(
            "DIS001", "error",
            "direct eviction/teardown on a drain/maintenance path outside "
            "the DrainController's sanctioned seam",
            "ISSUE 14: planned disruption is budgeted and accounted — the "
            "DrainController evicts with reason=Maintenance (free restart, "
            "budget-floored serve migration, one eviction per gang even "
            "when the node also dies). An ad-hoc evict_pod or Pod delete "
            "on a drain path bypasses the DisruptionBudget, burns the "
            "job's backoffLimit, and can double-tear the gang the "
            "controller is already migrating; route through the "
            "DrainController (or the serve controller's _drain_replica "
            "retire seam)",
        ),
        Rule(
            "CKP001", "error",
            "blocking checkpoint-commit wait in step-loop code outside "
            "the sanctioned final-checkpoint/restore/teardown seams",
            "ISSUE 16: periodic saves are async — the disk commit "
            "overlaps the next steps and the `ckpt` bucket charges only "
            "the blocking snapshot slice. A mgr.wait() / "
            "wait_until_finished() reached from the step loop "
            "re-serializes every save behind its fsync, resurrecting the "
            "periodic goodput stall the async path removed. Block only "
            "in the sanctioned seams: _final_checkpoint (SIGTERM "
            "force-checkpoint / terminal exit), restore (pre-restore "
            "fence), close (teardown)",
        ),
        Rule(
            "LEV001", "error",
            "handler derives decisions from the delivered event's payload",
            "ISSUE 19: watch deliveries are stale the moment they arrive — "
            "compaction, resync, dedup and leader failover all drop or "
            "reorder edges, so an event's embedded object is a snapshot of "
            "history, not of the cluster. A handler that reads "
            "event.obj.spec/.status is edge-triggered: it acts on the edge "
            "it happened to see and diverges the first time an edge is "
            "missed. Use the event only for identity (key/kind/metadata), "
            "re-read CURRENT state from the store/lister, and derive the "
            "decision from that — level triggers converge from any state",
        ),
        Rule(
            "AUTH001", "error",
            "route outside the authz permission matrix, or store state "
            "touched before the tier check",
            "ISSUE 20: authz_policy.json is the single source of "
            "authorization truth — a route literal served/compared in "
            "handler code with no matrix entry ships an endpoint whose "
            "posture nobody declared (authzcheck probes only what is "
            "declared, so the hole is invisible to the differ too). And "
            "reading/mutating store state BEFORE the tier check "
            "(_auth_error/_peer_denied/_agent_denied/_agent_patch_denied) "
            "re-opens the PR 2 TOCTOU: the state consulted for the "
            "decision can change between the touch and the gate — "
            "authorize first, touch state after",
        ),
        Rule(
            "REP001", "error",
            "direct store write on a follower/standby handle",
            "ISSUE 8: every mutation routes through the leased leader "
            "seam; a write applied directly to a follower's store forks "
            "the replica set's history (the fork no election can ever "
            "reconcile). The sanctioned follower write path is the "
            "replication apply seam (apply_replicated / install_snapshot "
            "/ append_entries / load_snapshot)",
        ),
    )
}

_DISABLE_RE = re.compile(r"#\s*oplint:\s*disable=([A-Za-z0-9_,\s]+)")

# receivers that look like a store (write surface) vs read-only surfaces;
# matching is on the LAST dotted component so `self.store`, `client.store`,
# `self.backing` and plain `store` all resolve the same way
_STORE_COMPONENTS = ("store", "backing")
_READER_COMPONENTS = ("read", "client")
_QUEUE_COMPONENTS = ("q", "queue")

_SECRET_RE = re.compile(r"token|secret|passw|credential|bearer", re.I)
_SECRET_EXEMPT_RE = re.compile(r"file|path|dir|name|kind|check|for|stats", re.I)
_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical", "log",
}
_HANDLER_NAME_RE = re.compile(
    r"^(run|_run.*|sync.*|_sync.*|_pump.*|reconcile.*|_reconcile.*)$"
    r"|.*(_loop|_worker|_handler)$"
)


def _dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as 'a.b.c' (None for anything else)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_component(dotted: Optional[str]) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _is_store_like(recv: Optional[str]) -> bool:
    last = _last_component(recv)
    return last in _STORE_COMPONENTS or last.endswith(_STORE_COMPONENTS)


def _is_reader_like(recv: Optional[str]) -> bool:
    last = _last_component(recv)
    return _is_store_like(recv) or last in _READER_COMPONENTS or last.endswith("client")


def _is_queue_like(recv: Optional[str]) -> bool:
    last = _last_component(recv)
    return last in _QUEUE_COMPONENTS or last.endswith(("_q", "_queue", "queue"))


def _is_secretish(name: str) -> bool:
    return bool(_SECRET_RE.search(name)) and not _SECRET_EXEMPT_RE.search(name)


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _const(node: Optional[ast.AST]):
    return node.value if isinstance(node, ast.Constant) else None


def _dict_keys(d: ast.Dict) -> Set[str]:
    return {k.value for k in d.keys if isinstance(k, ast.Constant)}


def _dict_value(d: ast.Dict, key: str) -> Optional[ast.expr]:
    for k, v in zip(d.keys, d.values):
        if isinstance(k, ast.Constant) and k.value == key:
            return v
    return None


# ---------------------------------------------------------------------------
# per-file checker
# ---------------------------------------------------------------------------


@dataclass
class _FileCtx:
    path: str
    is_test: bool
    findings: List[Finding] = field(default_factory=list)

    def report(self, rule_id: str, node: ast.AST, message: str) -> None:
        rule = RULES[rule_id]
        if rule.scope == "src" and self.is_test:
            return
        self.findings.append(
            Finding(
                rule_id, rule.severity, self.path,
                getattr(node, "lineno", 1), getattr(node, "col_offset", 0),
                message,
            )
        )


def _iter_functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _function_calls(fn: ast.AST) -> Iterable[ast.Call]:
    """Calls lexically inside ``fn``, excluding nested function bodies (a
    closure's get does not pair with the enclosing function's update)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_rmw001(ctx: _FileCtx, fn: ast.AST) -> None:
    reads: List[ast.Call] = []
    updates: List[Tuple[ast.Call, str]] = []
    for call in _function_calls(fn):
        if not isinstance(call.func, ast.Attribute):
            continue
        recv = _dotted(call.func.value)
        if call.func.attr in ("get", "try_get") and _is_reader_like(recv):
            reads.append(call)
        elif call.func.attr == "update" and _is_reader_like(recv):
            updates.append((call, recv or "?"))
    if reads and updates:
        for call, recv in updates:
            ctx.report(
                "RMW001", call,
                f"get+update read-modify-write through {recv!r}; use "
                f".patch with an rv precondition (or optimistic_update)",
            )


# LEV001: variables that hold a delivered watch event, by name or by a
# WatchEvent annotation (param or annotated local, the repo's pump idiom)
_EVENT_VAR_NAMES = {"event", "ev", "evt", "wevent", "watch_event"}
_EVENT_PAYLOAD_ATTRS = ("obj", "object")


def _is_watch_event_annotation(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return "WatchEvent" in ann.value
    return _last_component(_dotted(ann)) == "WatchEvent"


def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Nodes lexically inside ``fn``, excluding nested function bodies
    (those are visited as functions in their own right)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_lev001(ctx: _FileCtx, fn: ast.AST) -> None:
    # event variables: any event-named name (param, local, loop target —
    # the binding form doesn't change what the value is), plus anything
    # annotated WatchEvent under a non-standard name
    args = fn.args
    params = list(args.args) + list(args.kwonlyargs)
    params += list(getattr(args, "posonlyargs", []))
    event_vars: Set[str] = set(_EVENT_VAR_NAMES)
    for a in params:
        if _is_watch_event_annotation(a.annotation):
            event_vars.add(a.arg)
    for node in _own_nodes(fn):
        if (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and _is_watch_event_annotation(node.annotation)
        ):
            event_vars.add(node.target.id)
    for node in _own_nodes(fn):
        if not (isinstance(node, ast.Attribute) and node.attr in ("spec", "status")):
            continue
        inner = _dotted(node.value)
        if not inner:
            continue
        parts = inner.split(".")
        if (
            len(parts) == 2
            and parts[0] in event_vars
            and parts[1] in _EVENT_PAYLOAD_ATTRS
        ):
            ctx.report(
                "LEV001", node,
                f"decision read from the delivered event's payload "
                f"({inner}.{node.attr}); the payload is a stale snapshot — "
                f"take only the key from the event, re-read current state "
                f"from the store/lister, and decide from that",
            )


def _check_uid001(ctx: _FileCtx, call: ast.Call) -> None:
    if not (isinstance(call.func, ast.Attribute) and call.func.attr == "patch"):
        return
    if not _is_store_like(_dotted(call.func.value)):
        return
    kind = _const(call.args[0]) if call.args else None
    if kind not in ("Pod", "TPUJob"):
        return
    if _const(_kwarg(call, "subresource")) != "status":
        return
    patch = call.args[3] if len(call.args) > 3 else _kwarg(call, "patch")
    if not isinstance(patch, ast.Dict):
        return  # can't prove shape; the fixture suite pins the dict form
    meta = _dict_value(patch, "metadata")
    pinned = isinstance(meta, ast.Dict) and (
        _dict_keys(meta) & {"uid", "resource_version"}
    )
    if not pinned:
        ctx.report(
            "UID001", call,
            f"status write on {kind} without a metadata.uid or "
            f"resource_version precondition (a recreated same-name "
            f"incarnation could absorb it)",
        )


def _check_term001(ctx: _FileCtx, fn: ast.AST) -> None:
    phase_vars: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and tgt.attr == "phase"
                    and isinstance(tgt.value, ast.Attribute)
                    and tgt.value.attr == "status"
                    and isinstance(tgt.value.value, ast.Name)
                ):
                    phase_vars.add(tgt.value.value.id)
    for call in _function_calls(fn):
        if not (isinstance(call.func, ast.Attribute) and call.func.attr == "update"):
            continue
        if not _is_reader_like(_dotted(call.func.value)):
            continue
        if _const(_kwarg(call, "force")) is True:
            ctx.report(
                "TERM001", call,
                "force-PUT skips the rv check and can clobber a concurrent "
                "terminal write; use an rv-guarded patch",
            )
        elif (
            call.args
            and isinstance(call.args[0], ast.Name)
            and call.args[0].id in phase_vars
        ):
            ctx.report(
                "TERM001", call,
                f"writes {call.args[0].id}.status.phase via full-object PUT; "
                f"patch_pod_status/evict_pod enforce write-once-terminal",
            )


def _enclosing_handler(fn_stack: List[str]) -> bool:
    return bool(fn_stack) and bool(_HANDLER_NAME_RE.match(fn_stack[-1]))


def _check_blk001(ctx: _FileCtx, call: ast.Call, fn_stack: List[str]) -> None:
    func = call.func
    dotted = _dotted(func)
    if isinstance(func, ast.Attribute):
        recv = _dotted(func.value)
        if (
            func.attr == "get"
            and _is_queue_like(recv)
            and not call.args
            and _kwarg(call, "timeout") is None
        ):
            ctx.report(
                "BLK001", call,
                f"unbounded {recv}.get() can never observe shutdown; pass "
                f"timeout= and loop on the stop event",
            )
            return
        if (
            func.attr == "settimeout"
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value is None
        ):
            ctx.report("BLK001", call, "settimeout(None) disables the socket bound")
            return
    if dotted == "time.sleep" and _enclosing_handler(fn_stack):
        ctx.report(
            "BLK001", call,
            f"time.sleep in {fn_stack[-1]!r} cannot observe the stop event; "
            f"use Event.wait(timeout)",
        )
    elif dotted and dotted.rsplit(".", 1)[-1] == "urlopen":
        if _kwarg(call, "timeout") is None and len(call.args) < 3:
            ctx.report("BLK001", call, "urlopen without timeout= can hang forever")
    elif dotted and dotted.rsplit(".", 1)[-1] == "create_connection":
        if _kwarg(call, "timeout") is None and len(call.args) < 2:
            ctx.report(
                "BLK001", call, "create_connection without timeout= can hang forever"
            )


_CONN_COMPONENTS = ("conn", "connection")
_SQL_WRITE_RE = re.compile(
    r"^\s*(insert|update|delete|replace|create|drop|alter|begin|commit|"
    r"vacuum|reindex|attach|detach)\b",
    re.I,
)
_PRAGMA_SET_RE = re.compile(r"^\s*pragma\b[^=]*=", re.I)


def _is_conn_like(recv: Optional[str]) -> bool:
    last = _last_component(recv)
    return last in _CONN_COMPONENTS or last.endswith("conn")


def _in_txn_helper(fn_stack: List[str]) -> bool:
    """The sanctioned transaction helper itself (and helpers that ARE the
    seam, like a subclass override) may touch the connection directly."""
    return any(name == "_txn" or name.endswith("_txn") for name in fn_stack)


def _check_dur001_with(ctx: _FileCtx, node: ast.AST,
                       fn_stack: List[str]) -> None:
    """``with conn:`` is sqlite's transaction-commit context manager — a
    commit the ``_txn`` seam never announces."""
    if _in_txn_helper(fn_stack):
        return
    for item in node.items:
        expr = item.context_expr
        if _is_conn_like(_dotted(expr)):
            ctx.report(
                "DUR001", expr,
                f"`with {_dotted(expr)}:` commits a transaction outside "
                f"the sanctioned _txn helper; the crash-point explorer "
                f"cannot see this seam — route the write through _txn",
            )


def _check_dur001(ctx: _FileCtx, call: ast.Call,
                  fn_stack: List[str]) -> None:
    if _in_txn_helper(fn_stack):
        return
    f = call.func
    if not isinstance(f, ast.Attribute):
        return
    recv = _dotted(f.value)
    if not _is_conn_like(recv):
        return
    if f.attr in ("commit", "executescript"):
        ctx.report(
            "DUR001", call,
            f"{recv}.{f.attr}(...) mutates the store file outside the "
            f"sanctioned _txn helper; route the write through _txn so "
            f"the crash-point explorer sees its commit seam",
        )
        return
    if f.attr in ("execute", "executemany") and call.args:
        sql = _const(call.args[0])
        if isinstance(sql, str) and (
            _SQL_WRITE_RE.match(sql) or _PRAGMA_SET_RE.match(sql)
        ):
            ctx.report(
                "DUR001", call,
                f"write SQL through {recv}.{f.attr}(...) outside the "
                f"sanctioned _txn helper; an un-announced mutation can "
                f"split one logical write across transactions — a crash "
                f"between them strands an rv with no object",
            )


_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mu|mutex|cond)$")
_STORE_VERBS = {
    "get", "try_get", "update", "patch", "patch_batch", "list", "delete",
    "try_delete", "create", "watch",
}

# REP001: mutation verbs on a receiver whose dotted path names a
# follower/standby handle (`follower.update(...)`, `self.standby.store.
# delete(...)`). Matching is per-component so `follower.store.create`
# resolves like `follower.create`.
_MUTATION_VERBS = {
    "create", "update", "patch", "patch_batch", "delete", "try_delete",
}
_FOLLOWER_COMPONENT_RE = re.compile(
    r"(^|_)(follower|standby|replica|peer|joiner)s?$"
)
# functions that ARE the replication apply seam (and subclass overrides
# ending in these names): direct follower writes are their whole job.
# _handle_replica is the WIRE seam's server-side dispatcher (ISSUE 12);
# _pull_snapshot assembles the chunked transfer load_snapshot applies.
_REPLICATION_APPLY_FNS = {
    "apply_replicated", "install_snapshot", "append_entries",
    "load_snapshot", "_handle_replica", "_pull_snapshot",
}


def _is_follower_like(recv: Optional[str]) -> bool:
    if not recv:
        return False
    return any(
        _FOLLOWER_COMPONENT_RE.search(part) for part in recv.split(".")
    )


def _in_replication_apply(fn_stack: List[str]) -> bool:
    return any(name in _REPLICATION_APPLY_FNS for name in fn_stack)


def _check_rep001(ctx: _FileCtx, call: ast.Call,
                  fn_stack: List[str]) -> None:
    if _in_replication_apply(fn_stack):
        return
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _MUTATION_VERBS:
        return
    recv = _dotted(f.value)
    if _is_follower_like(recv):
        ctx.report(
            "REP001", call,
            f"store write {recv}.{f.attr}(...) on a follower handle "
            f"bypasses the leader seam and forks the replicated history; "
            f"route the mutation through the leader (followers only "
            f"write via the replication apply path)",
        )


# DIS001: teardown verbs reached from a drain/maintenance-flavored code
# path. Matching is by enclosing-function name (the same approximation
# REP001 uses for the replication seam): a function named for draining /
# evacuation / maintenance / migration that calls `evict_pod(...)` or
# deletes Pods directly is re-implementing the DrainController's job
# without its budget, dedupe, or free-restart accounting.
_DISRUPTION_FN_RE = re.compile(r"(^|_)(drain|evacuat|maintenan|migrat)", re.I)
# the sanctioned seam: the DrainController's own executors and the serve
# controller's gang-retire primitive (rollout + migration share it)
_DISRUPTION_SEAM_FNS = {
    "_migrate_batch_gangs", "_escalate", "_drain_replica",
    # the rescheduler's whole-gang free migration (ISSUE 18): its ONLY
    # direct eviction path — every other rescheduler move is a
    # maintenance stamp the DrainController executes
    "_migrate_gang",
}
_POD_DELETE_VERBS = {"delete", "try_delete"}


def _on_disruption_path(fn_stack: List[str]) -> bool:
    return any(_DISRUPTION_FN_RE.search(name) for name in fn_stack)


def _in_disruption_seam(fn_stack: List[str]) -> bool:
    return any(name in _DISRUPTION_SEAM_FNS for name in fn_stack)


def _check_dis001(ctx: _FileCtx, call: ast.Call,
                  fn_stack: List[str]) -> None:
    if not _on_disruption_path(fn_stack) or _in_disruption_seam(fn_stack):
        return
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    if name == "evict_pod":
        ctx.report(
            "DIS001", call,
            f"evict_pod(...) on the drain path {fn_stack[-1]!r} bypasses "
            f"the DrainController seam (budget floor, maintenance "
            f"free-restart accounting, one-eviction dedupe); stamp the "
            f"maintenance notice and let the controller evacuate",
        )
        return
    if (
        name in _POD_DELETE_VERBS
        and call.args
        and _const(call.args[0]) == "Pod"
    ):
        ctx.report(
            "DIS001", call,
            f"direct Pod {name}(...) on the drain path {fn_stack[-1]!r} "
            f"tears workload down outside the DrainController's "
            f"sanctioned seam; route through the drain plane (or the "
            f"serve controller's _drain_replica retire seam)",
        )


# CKP001: blocking checkpoint-commit waits reached from step-loop code.
# Matching mirrors DIS001/REP001: enclosing-function-name flavor for the
# path ("am I in train/elastic/step-loop code?"), receiver last-component
# flavor for the handle ("does this look like a checkpoint manager?"),
# and a seam-function exemption for the sanctioned blocking sites.
_CKPT_WAIT_VERBS = {"wait", "wait_until_finished"}
_CKPT_RECV_COMPONENTS = ("mgr", "manager", "ckpt", "checkpoint", "checkpointer")
_STEP_LOOP_FN_RE = re.compile(r"(^|_)(train|elastic|step_loop|run_steps)", re.I)
# the sanctioned blocking seams: the force-checkpoint/terminal-exit helper,
# the pre-restore fence, and teardown (ops/elastic.py, ops/checkpoint.py)
_CKPT_SEAM_FNS = {"_final_checkpoint", "restore", "close", "wait"}


def _is_ckpt_manager_like(recv: Optional[str]) -> bool:
    last = _last_component(recv)
    return last in _CKPT_RECV_COMPONENTS or last.endswith(_CKPT_RECV_COMPONENTS)


def _check_ckp001(ctx: _FileCtx, call: ast.Call,
                  fn_stack: List[str]) -> None:
    if not any(_STEP_LOOP_FN_RE.search(name) for name in fn_stack):
        return
    if any(name in _CKPT_SEAM_FNS for name in fn_stack):
        return
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _CKPT_WAIT_VERBS:
        return
    if not _is_ckpt_manager_like(_dotted(f.value)):
        return
    ctx.report(
        "CKP001", call,
        f"blocking checkpoint wait {f.attr}(...) in the step-loop path "
        f"{fn_stack[-1]!r} re-serializes async saves behind their disk "
        f"commit (the periodic `ckpt` goodput stall ISSUE 16 removed); "
        f"let the commit overlap and fence it only in the sanctioned "
        f"seams (_final_checkpoint / restore / close)",
    )


def _check_obs001(ctx: _FileCtx, call: ast.Call,
                  with_context_calls: Set[int]) -> None:
    """A ``start_span(...)`` call (any receiver — the module function,
    ``TRACER.start_span``, ``tr.start_span``) must BE the context
    expression of a ``with`` item. Assign-then-with still fires: the
    window between the call and the with is an exception path that leaks
    the open span."""
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    if name != "start_span":
        return
    if id(call) in with_context_calls:
        return
    ctx.report(
        "OBS001", call,
        "start_span() outside a with statement leaks the open span on "
        "the exception path (every later span re-parents under it); "
        "use `with start_span(...) as sp:`",
    )


# OBS003: metric registration + SLO-objective hygiene. The catalog is
# parsed (AST, never imported) from the canonical registry module next to
# this package, so lint stays side-effect free; registrations made in the
# linted file itself also count (fixtures and future modules registering
# their own families).
_REGISTRY_COMPONENTS = ("REGISTRY", "registry")
_METRIC_REG_VERBS = {"counter", "gauge", "histogram"}
_CATALOG_CACHE: Optional[Set[str]] = None


def _collect_registrations(tree: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_REG_VERBS
            and _last_component(_dotted(node.func.value))
            in _REGISTRY_COMPONENTS
        ):
            name = _const(node.args[0]) if node.args else None
            if isinstance(name, str):
                out.add(name)
    return out


def _registry_catalog() -> Optional[Set[str]]:
    """Family names the canonical registry (opshell/metrics.py) registers,
    AST-parsed once per process. None when the module cannot be found/
    parsed — the Objective half of OBS003 then stands down rather than
    false-firing on every objective."""
    global _CATALOG_CACHE
    if _CATALOG_CACHE is not None:
        return _CATALOG_CACHE
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opshell", "metrics.py",
    )
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    _CATALOG_CACHE = _collect_registrations(tree)
    return _CATALOG_CACHE


def _check_obs003(ctx: _FileCtx, call: ast.Call,
                  file_catalog: Set[str]) -> None:
    f = call.func
    if (
        isinstance(f, ast.Attribute)
        and f.attr in _METRIC_REG_VERBS
        and _last_component(_dotted(f.value)) in _REGISTRY_COMPONENTS
    ):
        name = _const(call.args[0]) if call.args else None
        help_arg = call.args[1] if len(call.args) > 1 else (
            _kwarg(call, "help_") or _kwarg(call, "help")
        )
        help_const = _const(help_arg)
        if help_arg is None or (isinstance(help_const, str)
                                and not help_const.strip()):
            ctx.report(
                "OBS003", call,
                f"{f.attr} {name or '?'!r} registered without non-empty "
                f"HELP text — the exposition's HELP line is what `ctl "
                f"top` and dashboards render at triage time",
            )
        return
    if isinstance(f, ast.Name) and f.id == "Objective":
        metric = _const(_kwarg(call, "metric"))
        if metric is None and len(call.args) > 1:
            metric = _const(call.args[1])
        if not isinstance(metric, str):
            return
        catalog = _registry_catalog()
        if catalog is None:
            return
        if metric not in catalog and metric not in file_catalog:
            ctx.report(
                "OBS003", call,
                f"SLO objective references metric family {metric!r} "
                f"absent from the registry catalog — it would silently "
                f"watch nothing (the config loader fails closed on this "
                f"at runtime)",
            )


# OBS004: a status-stats blob (the train_stats / serve_stats keys the
# executors mirror into pod status) must come out of the bounded-blob
# helpers. Recognized blessed shapes: the value is a DIRECT call to a
# helper, a name assigned from one in the same (or an enclosing)
# function scope, or None (clearing). Everything else — a raw dict, an
# unvetted parameter, a model's own sample() — fires: the lint cannot
# prove it bounded, and status blobs multiply across the watch fan-out.
_STATS_BLOB_KEYS = {"train_stats", "serve_stats"}
_BOUNDED_BLOB_FNS = {"bounded_train_stats", "bounded_serve_stats"}


def _check_obs004(ctx: _FileCtx, tree: ast.Module) -> None:
    def is_helper_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        return name in _BOUNDED_BLOB_FNS

    def blessed(node: ast.AST, names: Set[str]) -> bool:
        if is_helper_call(node):
            return True
        if isinstance(node, ast.Constant) and node.value is None:
            return True  # clearing the blob is always legal
        return isinstance(node, ast.Name) and node.id in names

    def scan(body, inherited: Set[str]) -> None:
        names = set(inherited)
        nested: List[ast.AST] = []
        nodes: List[ast.AST] = []
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.append(n)  # own scope; checked with inheritance
                continue
            nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))
        for n in nodes:  # pass 1: names assigned from a helper call
            if isinstance(n, ast.Assign) and is_helper_call(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        for n in nodes:  # pass 2: every blob construction site
            if isinstance(n, ast.Dict):
                for k, v in zip(n.keys, n.values):
                    if (
                        isinstance(k, ast.Constant)
                        and k.value in _STATS_BLOB_KEYS
                        and not blessed(v, names)
                    ):
                        ctx.report(
                            "OBS004", v,
                            f"status blob {k.value!r} built outside the "
                            f"bounded-blob helper — an unbounded dict "
                            f"here bloats every watch event carrying the "
                            f"pod; wrap it in bounded_"
                            f"{k.value.split('_')[0]}_stats(...)",
                        )
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and t.slice.value in _STATS_BLOB_KEYS
                        and not blessed(n.value, names)
                    ):
                        ctx.report(
                            "OBS004", n.value,
                            f"status blob {t.slice.value!r} assigned "
                            f"outside the bounded-blob helper; wrap it "
                            f"in bounded_"
                            f"{t.slice.value.split('_')[0]}_stats(...)",
                        )
        for fn in nested:
            scan(fn.body, names)

    scan(tree.body, set())


# span names that mark a CONTROLLER LOOP (the per-pass work of a
# level-triggered reconciler): these are the latencies PERF tracks and the
# SLO tripwires read, so their span-close function must observe a histogram
_LOOP_SPAN_RE = re.compile(r"\.(reconcile|sync)$")


def _check_obs002(ctx: _FileCtx, tree: ast.Module) -> None:
    """Every ``with start_span("<x>.reconcile"|"<x>.sync")`` must share a
    function with a histogram ``.observe(...)`` call — the OBS001
    companion: the with-form keeps the span honest, this keeps the
    span-close site instrumented (the pattern every controller loop since
    ISSUE 9 follows; a new loop that forgets is invisible to /metrics)."""

    def has_observe(fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "observe"
            ):
                return True
        return False

    def visit(node: ast.AST, fn: Optional[ast.AST]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = node
        if isinstance(node, (ast.With, ast.AsyncWith)) and fn is not None:
            for item in node.items:
                call = item.context_expr
                if not isinstance(call, ast.Call):
                    continue
                f = call.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if name != "start_span" or not call.args:
                    continue
                arg = call.args[0]
                if not (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and _LOOP_SPAN_RE.search(arg.value)
                ):
                    continue
                if not has_observe(fn):
                    ctx.report(
                        "OBS002", call,
                        f"controller-loop span {arg.value!r} closes in a "
                        f"function with no histogram .observe(...) — the "
                        f"span-close site is the instrumentation point "
                        f"(/metrics cannot see this loop's latency)",
                    )
        for child in ast.iter_child_nodes(node):
            visit(child, fn)

    visit(tree, None)


def _is_lock_expr(expr: ast.AST) -> bool:
    """Does a with-item context expression look like a lock? Matched on the
    LAST dotted component (`self._lock`, `self._mu`, `cache.lock`,
    `self._init_lock`, `self._cond` — a Condition holds its lock)."""
    return bool(_LOCK_NAME_RE.search(_last_component(_dotted(expr))))


def _check_lck001(ctx: _FileCtx, call: ast.Call) -> None:
    """Called only for calls lexically inside a lock-holding ``with``: a
    store verb on a store-like receiver, an ``urlopen``, or this repo's
    ``_request`` transport all block on I/O — held across them, one slow
    backend response stalls every contender on the lock."""
    f = call.func
    if isinstance(f, ast.Attribute):
        recv = _dotted(f.value)
        if f.attr in _STORE_VERBS and _is_reader_like(recv):
            ctx.report(
                "LCK001", call,
                f"store call {recv}.{f.attr}(...) while holding a lock; "
                f"one slow backend response stalls every contender — move "
                f"the call outside the lock",
            )
            return
        if f.attr == "_request":
            ctx.report(
                "LCK001", call,
                "HTTP transport call while holding a lock; the request "
                "timeout becomes every contender's stall bound",
            )
            return
    dotted = _dotted(f)
    if dotted and dotted.rsplit(".", 1)[-1] == "urlopen":
        ctx.report(
            "LCK001", call,
            "urlopen while holding a lock; the request timeout becomes "
            "every contender's stall bound",
        )


def _handler_logs_or_raises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                return True
            if isinstance(f, ast.Attribute) and f.attr in (
                _LOG_METHODS | {"print_exc"}
            ):
                return True
    return False


def _check_exc001(ctx: _FileCtx, handler: ast.ExceptHandler) -> None:
    if handler.type is None:
        ctx.report("EXC001", handler, "bare except: names no exception at all")
        return
    names = set()
    types = (
        handler.type.elts if isinstance(handler.type, ast.Tuple) else [handler.type]
    )
    for t in types:
        if isinstance(t, ast.Name):
            names.add(t.id)
    if names & {"Exception", "BaseException"} and not _handler_logs_or_raises(handler):
        ctx.report(
            "EXC001", handler,
            "broad except swallows the fault without logging or re-raising",
        )


def _secret_in(node: ast.AST) -> Optional[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and _is_secretish(sub.id):
            return sub.id
        if isinstance(sub, ast.Attribute) and _is_secretish(sub.attr):
            return sub.attr
    return None


def _check_sec001(ctx: _FileCtx, node: ast.AST) -> None:
    if isinstance(node, ast.Call):
        f = node.func
        is_log = (isinstance(f, ast.Name) and f.id == "print") or (
            isinstance(f, ast.Attribute) and f.attr in _LOG_METHODS
        )
        if is_log:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                leaked = _secret_in(arg)
                if leaked:
                    ctx.report(
                        "SEC001", arg,
                        f"secret-bearing value {leaked!r} formatted into log "
                        f"output; log the fact, never the value",
                    )
    elif isinstance(node, ast.JoinedStr):
        literal = "".join(
            v.value for v in node.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str)
        )
        if any(m in literal for m in ("http", "?", "&", "/v1/")):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    leaked = _secret_in(v.value)
                    if leaked:
                        ctx.report(
                            "SEC001", v,
                            f"secret-bearing value {leaked!r} interpolated "
                            f"into a URL; it would land in server logs and "
                            f"proxies",
                        )


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


def _disabled_lines(source: str) -> Dict[int, Set[str]]:
    """line number → set of rule ids disabled there. A trailing disable
    covers its own line ONLY; a disable inside a standalone comment block
    covers the first CODE line after the block (so multi-line reason
    comments — the suppression policy requires one — work naturally)."""
    lines = source.splitlines()
    out: Dict[int, Set[str]] = {}

    def add(i: int, rules: Set[str]) -> None:
        out.setdefault(i, set()).update(rules)

    for i, line in enumerate(lines, 1):
        m = _DISABLE_RE.search(line)
        if not m:
            continue
        rules = {r.strip().upper() for r in m.group(1).split(",") if r.strip()}
        add(i, rules)
        if line.lstrip().startswith("#"):
            j = i  # comment-only: propagate past the rest of the block
            while j < len(lines) and lines[j].lstrip().startswith("#"):
                j += 1
            add(j + 1, rules)
    return out


def is_test_path(path: str) -> bool:
    norm = path.replace(os.sep, "/")
    base = os.path.basename(norm)
    return (
        "/tests/" in norm
        or norm.startswith("tests/")
        or base.startswith(("test_", "conftest"))
    )


# ---------------------------------------------------------------------------
# AUTH001: the authorization plane's static cross-check (ISSUE 20). Half
# one: every route literal the server-side handler code compares its
# parsed path against must appear in analysis/authz_policy.json (the
# declared matrix authzcheck probes), peer wire tables included. Half
# two: within a function that runs one of the tier gates, no store-like
# receiver may be touched BEFORE the gate (the PR 2 TOCTOU shape).
# ---------------------------------------------------------------------------

_AUTH_GATE_NAMES = {
    "_auth_error", "_peer_denied", "_agent_denied", "_agent_patch_denied",
}
_PEER_TABLE_NAMES = {"_PEER_ROUTE_METHODS", "PEER_ROUTES"}
_AUTHZ_PATHS_CACHE: Optional[List[List[str]]] = None
_AUTHZ_PATHS_LOADED = False


def _authz_declared_paths() -> Optional[List[List[str]]]:
    """Path patterns authz_policy.json declares (method stripped, split
    into segments), loaded once per process from the canonical file next
    to this module. None when the policy cannot be found/parsed — the
    route half of AUTH001 then stands down rather than false-firing on
    every route literal in the tree."""
    global _AUTHZ_PATHS_CACHE, _AUTHZ_PATHS_LOADED
    if _AUTHZ_PATHS_LOADED:
        return _AUTHZ_PATHS_CACHE
    _AUTHZ_PATHS_LOADED = True
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "authz_policy.json"
    )
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        routes = doc["routes"]
        if not isinstance(routes, dict):
            return None
    except (OSError, ValueError, KeyError):
        return None
    out: List[List[str]] = []
    for key in routes:
        if isinstance(key, str) and " " in key:
            out.append(key.split(" ", 1)[1].strip("/").split("/"))
    _AUTHZ_PATHS_CACHE = out
    return _AUTHZ_PATHS_CACHE


def _auth001_declared(segs: List[str], declared: List[List[str]]) -> bool:
    """True when the concrete segment list is a (placeholder-tolerant)
    prefix of some declared path — ``["v1", "objects", "TPUServe"]``
    matches ``/v1/objects/{kind}``; ``["v1", "replica"]`` matches
    ``/v1/replica/status``."""
    for pat in declared:
        if len(segs) > len(pat):
            continue
        if all(
            p == s or (p.startswith("{") and p.endswith("}"))
            for s, p in zip(segs, pat)
        ):
            return True
    return False


def _auth001_route_lists(node: ast.Compare) -> List[ast.List]:
    """The list literals a route-parts comparison checks against —
    handles ``parts == [...]``, ``parts[:2] == [...]`` and
    ``_route_parts(p) in ([...], [...])``."""
    left = node.left
    base = left.value if isinstance(left, ast.Subscript) else left
    is_parts = _last_component(_dotted(base)) == "parts"
    if not is_parts and isinstance(base, ast.Call):
        is_parts = _last_component(_dotted(base.func)) == "_route_parts"
    if not is_parts:
        return []
    out: List[ast.List] = []
    for comp in node.comparators:
        if isinstance(comp, ast.List):
            out.append(comp)
        elif isinstance(comp, ast.Tuple):
            out.extend(e for e in comp.elts if isinstance(e, ast.List))
    return out


def _check_auth001_routes(ctx: _FileCtx, tree: ast.AST) -> None:
    declared = _authz_declared_paths()
    if declared is None:
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.Compare):
            for lst in _auth001_route_lists(node):
                segs = [
                    e.value for e in lst.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                if len(segs) != len(lst.elts) or not segs:
                    continue
                if segs[0] not in ("v1", "healthz"):
                    continue
                if not _auth001_declared(segs, declared):
                    route = "/" + "/".join(segs)
                    ctx.report(
                        "AUTH001", lst,
                        f"route {route!r} is served here but has no entry "
                        f"in analysis/authz_policy.json — declare its "
                        f"authorization posture before it ships",
                    )
        elif isinstance(node, ast.Assign):
            names = {t.id for t in node.targets if isinstance(t, ast.Name)}
            if not (names & _PEER_TABLE_NAMES):
                continue
            if not isinstance(node.value, ast.Dict):
                continue
            # the two peer tables are inverse orientations (server:
            # wire-route → method name; client fabric: method → wire
            # route), so the wire segment may sit on either side of a
            # pair — an entry is declared when EITHER side matches
            for key, val in zip(node.value.keys, node.value.values):
                sides = [s for s in (_const(key), _const(val))
                         if isinstance(s, str)]
                if not sides:
                    continue
                if not any(
                    _auth001_declared(["v1", "replica", side], declared)
                    for side in sides
                ):
                    wire = next((s for s in sides if "-" in s), sides[0])
                    ctx.report(
                        "AUTH001", val,
                        f"peer wire route '/v1/replica/{wire}' has "
                        f"no entry in analysis/authz_policy.json — the "
                        f"peer tables and the matrix must agree",
                    )


def _check_auth001_toctou(ctx: _FileCtx, fn: ast.AST) -> None:
    calls = [n for n in _own_nodes(fn) if isinstance(n, ast.Call)]
    auth_lines = [
        c.lineno for c in calls
        if isinstance(c.func, ast.Attribute) and c.func.attr in _AUTH_GATE_NAMES
    ]
    if not auth_lines:
        return
    last_auth = max(auth_lines)
    for c in calls:
        if not isinstance(c.func, ast.Attribute):
            continue
        if c.func.attr in _AUTH_GATE_NAMES:
            continue
        recv = _dotted(c.func.value)
        if _is_store_like(recv) and c.lineno < last_auth:
            ctx.report(
                "AUTH001", c,
                f"store state touched through {recv!r} BEFORE the tier "
                f"check on line {last_auth} — authorize first, touch "
                f"state after (the PR 2 TOCTOU)",
            )


def lint_source(
    source: str, path: str = "<string>", *, is_test: Optional[bool] = None
) -> List[Finding]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                "E999", "error", path, e.lineno or 1, e.offset or 0,
                f"syntax error: {e.msg}",
            )
        ]
    ctx = _FileCtx(path, is_test_path(path) if is_test is None else is_test)

    for fn in _iter_functions(tree):
        _check_rmw001(ctx, fn)
        _check_term001(ctx, fn)
        _check_lev001(ctx, fn)
        _check_auth001_toctou(ctx, fn)
    _check_obs002(ctx, tree)
    _check_obs004(ctx, tree)
    _check_auth001_routes(ctx, tree)

    # pre-pass for OBS003: families this file registers itself count
    # toward the catalog (a module may register and reference its own)
    file_catalog = _collect_registrations(tree)

    # pre-pass for OBS001: the set of Call nodes that ARE a with item's
    # context expression (the blessed span shape)
    with_context_calls: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    with_context_calls.add(id(item.context_expr))

    # walk with an enclosing-function-name stack for BLK001's sleep check
    # and a held-lock depth for LCK001 (a nested def's body does not run
    # under the enclosing with, so the depth resets at function boundaries)
    def visit(node: ast.AST, fn_stack: List[str], lock_depth: int) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_stack = fn_stack + [node.name]
            lock_depth = 0
        if isinstance(node, (ast.With, ast.AsyncWith)):
            _check_dur001_with(ctx, node, fn_stack)
            if any(_is_lock_expr(item.context_expr) for item in node.items):
                lock_depth += 1
        if isinstance(node, ast.Call):
            _check_uid001(ctx, node)
            _check_blk001(ctx, node, fn_stack)
            _check_dur001(ctx, node, fn_stack)
            _check_rep001(ctx, node, fn_stack)
            _check_dis001(ctx, node, fn_stack)
            _check_ckp001(ctx, node, fn_stack)
            _check_obs001(ctx, node, with_context_calls)
            _check_obs003(ctx, node, file_catalog)
            if lock_depth > 0:
                _check_lck001(ctx, node)
        if isinstance(node, ast.ExceptHandler):
            _check_exc001(ctx, node)
        _check_sec001(ctx, node)
        for child in ast.iter_child_nodes(node):
            visit(child, fn_stack, lock_depth)

    visit(tree, [], 0)

    disabled = _disabled_lines(source)
    out = []
    for f in ctx.findings:
        if f.rule_id in disabled.get(f.line, set()):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    return out


# directories never linted: caches, plus the fixture corpus that is bad on
# purpose. The data skip is SCOPED to a tests directory's data/ — a source
# module living under some other directory named data must not silently
# escape the gate this linter exists to provide.
_SKIP_DIRS = {"__pycache__", ".git", "node_modules"}


def _skip_dir(root: str, name: str) -> bool:
    if name in _SKIP_DIRS:
        return True
    return name == "data" and os.path.basename(root) == "tests"


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if not _skip_dir(root, d))
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        with open(path, encoding="utf-8") as f:
            findings.extend(lint_source(f.read(), path))
    return findings


def rule_catalog() -> str:
    lines = []
    for rule in RULES.values():
        fix = " [autofixable]" if rule.autofixable else ""
        lines.append(f"{rule.id} ({rule.severity}, scope={rule.scope}){fix}")
        lines.append(f"  {rule.summary}")
        lines.append(f"  why: {rule.rationale}")
    return "\n".join(lines)
