"""The sequential store specification, in two executable forms.

The store contract PRs 1-5 grew — rv-preconditioned optimistic concurrency,
uid-pinned incarnation writes, the frozen status subresource, a global
strictly-increasing resource_version sequence, watch events in commit
order — is a SEQUENTIAL spec. Two tools check real backends against it and
they must share ONE model or the spec itself forks:

- :class:`StoreModel` (promoted here from ``analysis/linearize.py``) is the
  *validator* form: given a per-key abstract state and one op's RECORDED
  result, is that result possible? The linearizability checker's
  branch-pruning oracle.
- :class:`ModelStore` is the *generator* form: a complete sequential
  reference implementation of the five verbs + status subresource +
  ``patch_batch`` + watch event log, operating on plain encoded dicts. The
  differential fuzzer (:mod:`analysis.storecheck`) executes every op
  sequence against it and diffs the three real backends' return values,
  error classes, final state and watch streams against its answers.

``ModelStore`` deliberately reuses :func:`apply_merge_patch_dict` — the
shared semantic core all three backends already ride — so the *merge*
algebra cannot drift between model and subject (differential testing can
never see a bug every implementation shares anyway); everything the
backends implement separately (rv stamping, preconditions, existence,
watch delivery, batch semantics) is modeled independently.

``ModelStore`` also self-checks: every op it executes is replayed through
``StoreModel.apply`` (:meth:`ModelStore.apply_op` raises
:class:`ModelDrift` on disagreement), so the fuzzer's oracle and the
linearizability checker's oracle are mechanically pinned to each other —
the replicated-store acceptance bar (ROADMAP item 1) is one spec, not two.

One deliberate asymmetry: StoreModel encodes the SYSTEM spec — clients
write Pod phases through ``patch_pod_status``, which makes terminal phases
write-once — while the raw store accepts a phase-resurrecting status patch
(the guard lives in the helper, not the server). The fuzzer's generator
therefore never emits that op class (it clamps phase writes at resolution
time, storecheck._resolve), the same way real clients never do.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from mpi_operator_tpu.machinery.serialize import decode, encode
from mpi_operator_tpu.machinery.store import (
    AlreadyExists,
    BadPatch,
    Conflict,
    NotFound,
    apply_merge_patch_dict,
)

TERMINAL_PHASES = ("Succeeded", "Failed")

# results a store verb may legally resolve to; anything else recorded as an
# error is treated as state-independent (a caller bug like BadPatch can
# linearize anywhere without touching state)
STATE_ERRORS = ("NotFound", "Conflict", "AlreadyExists")

# per-key model state: (exists, rv, uid, phase)
State = Tuple[bool, int, Optional[str], Optional[str]]
INITIAL: State = (False, 0, None, None)


class StoreModel:
    """Legality of one op's recorded result against a per-key state.
    ``apply`` returns the successor state, or None when the recorded
    result is impossible in this state — the checker's branch-pruning
    oracle. Ops are ``linearize.OpRecord``-shaped (duck-typed: ``op``,
    ``kind``, ``args``, ``result`` attributes)."""

    @staticmethod
    def apply(state: State, op: Any) -> Optional[State]:
        exists, rv, uid, phase = state
        err = op.result.get("error")
        if err is not None:
            if err == "NotFound":
                return state if not exists else None
            if err == "AlreadyExists":
                return state if (op.op == "create" and exists) else None
            if err == "Conflict":
                if not exists:
                    return None
                if op.op == "update":
                    ok = (not op.args.get("force")) and op.args.get("rv") != rv
                    return state if ok else None
                if op.op == "patch":
                    p_rv = op.args.get("precond_rv")
                    p_uid = op.args.get("precond_uid")
                    ok = (p_rv is not None and p_rv != rv) or (
                        p_uid is not None and p_uid != uid
                    )
                    return state if ok else None
                return None
            # BadPatch / Unauthorized / ... : state-independent caller bug
            return state
        new_rv = op.result.get("rv")
        new_phase = op.result.get("phase", phase)
        if op.op == "get":
            return state if (exists and new_rv == rv) else None
        if op.op == "create":
            if exists:
                return None
            return (True, new_rv, op.result.get("uid"), new_phase)
        if not exists or new_rv is None or new_rv <= rv:
            return None  # writes need a live object and a fresh rv
        if op.op == "update":
            if not op.args.get("force") and op.args.get("rv") != rv:
                return None
            return (True, new_rv, uid, new_phase)
        if op.op == "patch":
            p_rv = op.args.get("precond_rv")
            p_uid = op.args.get("precond_uid")
            if p_rv is not None and p_rv != rv:
                return None
            if p_uid is not None and p_uid != uid:
                return None
            if (
                op.kind == "Pod"
                and op.args.get("subresource") == "status"
                and phase in TERMINAL_PHASES
                and new_phase != phase
            ):
                # terminal write-once: a status patch may never resurrect a
                # finished pod (the PR 2 contract patch_pod_status enforces;
                # full-object force-PUTs — test fixtures playing kubelet —
                # are deliberately exempt)
                return None
            return (True, new_rv, uid, new_phase)
        if op.op == "delete":
            return (False, new_rv, None, None)
        return state  # unknown verb: recorded for completeness, no model


class ModelDrift(RuntimeError):
    """ModelStore produced a result StoreModel.apply rejects: the two
    forms of the sequential spec disagree — a tooling bug, never a backend
    finding."""


class _ModelOp:
    """Duck-typed OpRecord stand-in for the StoreModel cross-check."""

    __slots__ = ("op", "kind", "args", "result")

    def __init__(self, op: str, kind: str, args: Dict[str, Any],
                 result: Dict[str, Any]):
        self.op = op
        self.kind = kind
        self.args = args
        self.result = result


def _normalize(kind: str, d: Dict[str, Any]) -> Dict[str, Any]:
    """Round-trip an encoded dict through the kind's dataclass so the
    model stores exactly the pruned shape the backends return (default
    fields dropped, aliases resolved) — dict equality against a backend's
    ``encode(obj)`` is then exact, not modulo pruning."""
    return encode(decode(kind, d))


class ModelStore:
    """Sequential reference store over encoded dicts. Same verb surface
    and error classes as the three real backends; results come back as the
    committed encoded object (a write) or raise the store error class —
    exactly what the fuzzer normalizes backend results to."""

    def __init__(self):
        self._objects: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        self._rv = 0
        # every committed write, in commit order: (etype, kind, ns, name,
        # rv, encoded-object) — the reference watch stream AND the ring
        # model watch_resume diffs against
        self.events: List[Tuple[str, str, str, str, int, Dict[str, Any]]] = []
        # per-key abstract state for the StoreModel cross-check
        self._abstract: Dict[Tuple[str, str, str], State] = {}

    # -- helpers -------------------------------------------------------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    @staticmethod
    def _key(kind: str, ns: str, name: str) -> Tuple[str, str, str]:
        return (kind, ns, name)

    @staticmethod
    def _meta(d: Dict[str, Any]) -> Dict[str, Any]:
        return d.get("metadata") or {}

    @staticmethod
    def _phase(d: Dict[str, Any]) -> Optional[str]:
        ph = (d.get("status") or {}).get("phase")
        return str(ph) if ph is not None else None

    def current_rv(self) -> int:
        return self._rv

    def _emit(self, etype: str, kind: str, ns: str, name: str, rv: int,
              obj: Dict[str, Any]) -> None:
        self.events.append((etype, kind, ns, name, rv, obj))

    def _cross_check(self, op: str, kind: str, ns: str, name: str,
                     args: Dict[str, Any], result: Dict[str, Any]) -> None:
        """Replay the op through StoreModel.apply; the two spec forms must
        agree or the tooling itself is broken (ModelDrift)."""
        key = self._key(kind, ns, name)
        state = self._abstract.get(key, INITIAL)
        nxt = StoreModel.apply(state, _ModelOp(op, kind, args, result))
        if nxt is None:
            raise ModelDrift(
                f"ModelStore result for {op}({kind} {ns}/{name}, "
                f"args={args!r}) -> {result!r} is rejected by "
                f"StoreModel.apply in state {state!r}"
            )
        self._abstract[key] = nxt

    def _ok_result(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        m = self._meta(obj)
        out: Dict[str, Any] = {"rv": m.get("resource_version"),
                               "uid": m.get("uid")}
        ph = self._phase(obj)
        if ph is not None:
            out["phase"] = ph
        return out

    # -- verbs ---------------------------------------------------------------

    def create(self, kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        obj = _normalize(kind, obj)
        m = self._meta(obj)
        ns, name = m.get("namespace", "default"), m.get("name", "")
        key = self._key(kind, ns, name)
        if key in self._objects:
            self._cross_check("create", kind, ns, name, {},
                              {"error": "AlreadyExists"})
            raise AlreadyExists(f"{kind} {ns}/{name} already exists")
        rv = self._next_rv()
        obj.setdefault("metadata", {})["resource_version"] = rv
        obj = _normalize(kind, obj)
        self._objects[key] = obj
        self._emit("ADDED", kind, ns, name, rv, obj)
        self._cross_check("create", kind, ns, name, {}, self._ok_result(obj))
        return obj

    def get(self, kind: str, ns: str, name: str) -> Dict[str, Any]:
        key = self._key(kind, ns, name)
        if key not in self._objects:
            self._cross_check("get", kind, ns, name, {},
                              {"error": "NotFound"})
            raise NotFound(f"{kind} {ns}/{name} not found")
        obj = self._objects[key]
        self._cross_check("get", kind, ns, name, {}, self._ok_result(obj))
        return obj

    def update(self, kind: str, obj: Dict[str, Any],
               force: bool = False) -> Dict[str, Any]:
        obj = _normalize(kind, obj)
        m = self._meta(obj)
        ns, name = m.get("namespace", "default"), m.get("name", "")
        key = self._key(kind, ns, name)
        args = {"rv": m.get("resource_version", 0), "force": bool(force)}
        if key not in self._objects:
            self._cross_check("update", kind, ns, name, args,
                              {"error": "NotFound"})
            raise NotFound(f"{kind} {ns}/{name} not found")
        cur_rv = self._meta(self._objects[key]).get("resource_version", 0)
        if not force and m.get("resource_version", 0) != cur_rv:
            self._cross_check("update", kind, ns, name, args,
                              {"error": "Conflict"})
            raise Conflict(
                f"{kind} {ns}/{name}: resource_version "
                f"{m.get('resource_version')} != {cur_rv}"
            )
        rv = self._next_rv()
        obj["metadata"]["resource_version"] = rv
        obj = _normalize(kind, obj)
        self._objects[key] = obj
        self._emit("MODIFIED", kind, ns, name, rv, obj)
        self._cross_check("update", kind, ns, name, args,
                          self._ok_result(obj))
        return obj

    def patch(self, kind: str, ns: str, name: str, patch: Any, *,
              subresource: Optional[str] = None) -> Dict[str, Any]:
        meta_patch = patch.get("metadata") if isinstance(patch, dict) else None
        args: Dict[str, Any] = {"subresource": subresource}
        if isinstance(meta_patch, dict):
            if meta_patch.get("resource_version") is not None:
                args["precond_rv"] = meta_patch["resource_version"]
            if meta_patch.get("uid") is not None:
                args["precond_uid"] = meta_patch["uid"]
        key = self._key(kind, ns, name)
        if key not in self._objects:
            self._cross_check("patch", kind, ns, name, args,
                              {"error": "NotFound"})
            raise NotFound(f"{kind} {ns}/{name} not found")
        cur = self._objects[key]
        try:
            merged = apply_merge_patch_dict(
                kind, cur, patch, subresource=subresource,
                current_rv=self._meta(cur).get("resource_version", 0),
            )
        except (BadPatch, Conflict) as e:
            self._cross_check("patch", kind, ns, name, args,
                              {"error": type(e).__name__})
            raise
        rv = self._next_rv()
        # apply_merge_patch_dict returns a SHALLOW copy (its metadata dict
        # is the stored object's): stamp the rv on a fresh metadata dict,
        # or a same-key patch later in one patch_batch would mutate the
        # result an earlier item already returned (the real backends
        # deepcopy at their verb boundary; the model must be as careful)
        merged = dict(merged, metadata=dict(merged.get("metadata") or {}))
        merged["metadata"]["resource_version"] = rv
        merged = _normalize(kind, merged)
        self._objects[key] = merged
        self._emit("MODIFIED", kind, ns, name, rv, merged)
        self._cross_check("patch", kind, ns, name, args,
                          self._ok_result(merged))
        return merged

    def patch_batch(self, items: List[Dict[str, Any]]) -> List[Any]:
        """The shared patch_batch contract (store.patch_batch_via_loop):
        items apply IN ORDER, each atomic on its own, per-item errors as
        exception VALUES — a mid-batch failure leaves the prefix applied
        and never blocks the suffix."""
        out: List[Any] = []
        for it in items:
            try:
                if not isinstance(it, dict):
                    raise BadPatch("batch item must be an object")
                out.append(
                    self.patch(
                        it["kind"], it["namespace"], it["name"],
                        it.get("patch"), subresource=it.get("subresource"),
                    )
                )
            except (NotFound, Conflict, BadPatch) as e:
                out.append(e)
            except KeyError as e:
                out.append(BadPatch(f"batch item missing {e}"))
        return out

    def delete(self, kind: str, ns: str, name: str) -> Dict[str, Any]:
        key = self._key(kind, ns, name)
        if key not in self._objects:
            self._cross_check("delete", kind, ns, name, {},
                              {"error": "NotFound"})
            raise NotFound(f"{kind} {ns}/{name} not found")
        obj = self._objects.pop(key)
        # deletion consumes a resource_version (every backend does): watch
        # events carry strictly increasing rvs, the resume anchor
        rv = self._next_rv()
        obj = dict(obj)
        obj.setdefault("metadata", {})
        obj["metadata"] = dict(obj["metadata"], resource_version=rv)
        obj = _normalize(kind, obj)
        self._emit("DELETED", kind, ns, name, rv, obj)
        self._cross_check("delete", kind, ns, name, {},
                          self._ok_result(obj))
        return obj

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None
             ) -> List[Dict[str, Any]]:
        out = []
        for (k, ns, _name), obj in self._objects.items():
            if k != kind:
                continue
            if namespace is not None and ns != namespace:
                continue
            if selector:
                lbls = self._meta(obj).get("labels") or {}
                if any(lbls.get(sk) != sv for sk, sv in selector.items()):
                    continue
            out.append(obj)
        out.sort(key=lambda o: (self._meta(o).get("namespace", ""),
                                self._meta(o).get("name", "")))
        return out

    # -- final-state / watch views ------------------------------------------

    def snapshot(self) -> Dict[Tuple[str, str, str], Dict[str, Any]]:
        """The complete live state, keyed by (kind, ns, name) — the
        final-state side of the differential diff."""
        return dict(self._objects)

    def watch_stream(self) -> List[Tuple[str, str, str, str, int]]:
        """(etype, kind, ns, name, rv) per committed write, in commit
        order — what a watcher registered before the first op must
        deliver."""
        return [(e, k, ns, n, rv) for (e, k, ns, n, rv, _o) in self.events]

    # -- the http event-ring model (watch_resume oracle) ---------------------

    def ring_dropped_rv(self, capacity: int) -> int:
        """Highest rv trimmed out of a ring of ``capacity`` fed every
        event since rv 0 (mirrors http_store._EventLog._dropped_rv)."""
        n = len(self.events)
        if n <= capacity:
            return 0
        return max(e[4] for e in self.events[: n - capacity])

    def resume_after_rv(
        self, rv: int, capacity: int
    ) -> Optional[List[Tuple[str, str, str, str, int]]]:
        """The spec of ``_EventLog.resume_after_rv`` for a server whose
        ring (capacity ``capacity``, base rv 0) saw every model event:
        the tail with object rv > ``rv``, or None when completeness is
        not provable (anchor below the trim horizon or above everything
        vouched for) — the caller must relist."""
        if rv < self.ring_dropped_rv(capacity):
            return None
        if rv > self._rv:
            return None
        return [
            (e, k, ns, n, erv)
            for (e, k, ns, n, erv, _o) in self.events
            if erv > rv
        ]
