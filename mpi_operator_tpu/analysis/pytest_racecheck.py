"""Opt-in pytest plugin running the racecheck detector over a test session.

Usage (the slow-tier job; see README "Static analysis & race checking"):

    python -m pytest tests/test_cache.py tests/test_stress.py -q \\
        -p mpi_operator_tpu.analysis.pytest_racecheck --racecheck

With ``--racecheck`` the tracked lock factories are installed for the whole
session and the control-plane classes (racecheck.DEFAULT_TARGETS) are
instrumented; at session end a summary is printed and ANY finding fails the
run. Without the flag the plugin is inert, so it is always safe to load.
"""

from __future__ import annotations


def pytest_addoption(parser):
    parser.addoption(
        "--racecheck", action="store_true", default=False,
        help="run the whole session under the lock-order + shared-state "
             "race detector (mpi_operator_tpu.analysis.racecheck)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "racecheck: tests exercising (or exercised under) the race detector",
    )
    if config.getoption("--racecheck"):
        from mpi_operator_tpu.analysis import racecheck

        # the nearest .racecheck-allow (rootdir-style resolution) names
        # the deliberate patterns, each with a reason — file-side
        # suppression, so exceptions stop hiding in code-side exemptions
        allow_path = racecheck.find_allowlist(str(config.rootdir))
        allowlist = (
            racecheck.load_allowlist(allow_path) if allow_path else None
        )
        config._racecheck_session = racecheck.Session(
            allowlist=allowlist
        ).install()


def pytest_sessionfinish(session, exitstatus):
    sess = getattr(session.config, "_racecheck_session", None)
    if sess is None:
        return
    sess.uninstall()
    if sess.findings() and exitstatus == 0:
        session.exitstatus = 1


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    sess = getattr(config, "_racecheck_session", None)
    if sess is None:
        return
    terminalreporter.section("racecheck")
    terminalreporter.write_line(sess.render_report())
