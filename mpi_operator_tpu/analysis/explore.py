"""opcheck explorer: deterministic thread-interleaving exploration.

racecheck (PR 4) observes ONE schedule per run — whatever the OS happened
to produce — so a latent atomicity violation stays latent until a chaos
replay trips it. This module takes the opposite stance, after CHESS
(Musuvathi et al., iterative context bounding): a **cooperative scheduler**
takes over every scheduling-relevant operation and runs exactly one thread
at a time, so the interleaving IS data — enumerable, boundable, and
replayable from a printed token.

How control is seized:

- the ``threading.Lock``/``RLock``/``Condition`` factories are patched (the
  same seam racecheck uses) into **bookkeeping primitives**: because only
  one managed thread ever runs, mutual exclusion needs no OS lock — an
  acquire is a *scheduling request* (the thread becomes runnable only when
  the lock is free), a blocked ``Condition.wait`` parks the thread until a
  notify. ``queue.Queue`` built inside the window inherits these and turns
  cooperative for free.
- store/workqueue/cache ops announce themselves through
  ``machinery.yieldpoints`` (get/put/patch/list/watch-deliver...), adding
  the context-switch points where lost updates actually live — between a
  read and the write built on it, where no lock operation happens.

Exploration is stateless (re-execute per schedule) with **bounded
preemption**: the default policy runs each thread until it blocks; a
*deviation* ``{step: thread}`` forces a preemption at one choice point.
Systematic mode enumerates deviation sets of size ≤ the preemption bound
(CHESS's insight: most concurrency bugs need ≤ 2 preemptions); random mode
samples seeded deviation sets. Every failing run prints a compact
**schedule token** (``v1:<scenario>:<step>=<thread>,...``) and
``--replay <token>`` re-executes that exact interleaving — concurrency
bugs become reproducible-by-token instead of flaky.

Failures the explorer reports: an invariant check raising, a thread dying
on an exception, and **deadlock** (no thread runnable — a lost wakeup or a
lock cycle actually interleaved into, not just potential like racecheck's
edges).

Scenario constraints (enforced by construction, documented here): scenario
threads are spawned by the scheduler (not ``threading.Thread``), must not
sleep on wall-clock time, and must not start background OS threads —
an unmanaged thread blocking on a managed primitive raises ExploreError.
"""

from __future__ import annotations

import _thread
import random as _random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from mpi_operator_tpu.machinery import yieldpoints

# thread states
_RUNNABLE = "runnable"
_DONE = "done"

TOKEN_VERSION = "v1"


class ExploreError(RuntimeError):
    """The exploration machinery itself failed (bad token, unmanaged thread
    blocked on a managed primitive, step budget exhausted) — distinct from
    a scenario FAILURE, which is a finding."""


class _Aborted(BaseException):
    """Raised inside parked scenario threads when a run is being torn down
    (deadlock finding / step-budget abort): BaseException so scenario
    ``except Exception`` blocks cannot swallow the unwind."""


@dataclass
class ExploreBudget:
    """Exploration bounds. ``max_preemptions`` is the CHESS context bound
    (deviations per schedule); ``max_runs`` caps total re-executions;
    ``max_steps`` guards a single run against wall-clock spin (a timed
    wait polled in a loop)."""

    max_runs: int = 80
    max_preemptions: int = 2
    max_steps: int = 20000


FAST_BUDGET = ExploreBudget(max_runs=80, max_preemptions=2)
# the slow-tier budget: enough runs to exhaust every ≤2-preemption schedule
# of the shipped scenarios and a deeper bound on top
EXHAUSTIVE_BUDGET = ExploreBudget(max_runs=4000, max_preemptions=3)


class _Gate:
    """Binary handoff on a raw ``_thread`` lock (deliberately below the
    patched ``threading`` factories): starts closed; ``wait()`` blocks
    until another thread ``open()``s it, consuming the open."""

    __slots__ = ("_lk",)

    def __init__(self):
        self._lk = _thread.allocate_lock()
        self._lk.acquire()

    def wait(self) -> None:
        self._lk.acquire()

    def open(self) -> None:
        self._lk.release()


@dataclass
class _MThread:
    index: int
    name: str
    fn: Callable[[], None]
    gate: _Gate = field(default_factory=_Gate)
    ident: Optional[int] = None
    state: str = _RUNNABLE
    # scheduling constraints, set while parked at a yield point
    wait_lock: Optional["ManagedLock"] = None
    wait_cond: Optional["ManagedCondition"] = None
    timed: bool = False
    notified: bool = False
    last_label: str = "start"
    exc: Optional[BaseException] = None


class ManagedLock:
    """Lock under the cooperative scheduler: pure bookkeeping (owner +
    recursion count). Acquire from a managed thread is a scheduling
    request; from an unmanaged thread it succeeds only when free (an
    unmanaged thread can never cooperatively block). Named from a
    PER-SCHEDULER counter so a replayed run labels its locks identically
    to the original — trace/failure equality across replays is part of
    the determinism contract."""

    def __init__(self, sched: "_Scheduler", reentrant: bool):
        self._sched = sched
        self._reentrant = reentrant
        self.owner: Optional[int] = None  # _MThread.index
        self.count = 0
        sched._lock_seq += 1
        self.name = f"{'RLock' if reentrant else 'Lock'}#{sched._lock_seq}"

    # -- lock protocol ------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._sched.lock_acquire(self, blocking, timeout)

    def release(self) -> None:
        self._sched.lock_release(self)

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition protocol (threading.Condition fallback relies on
    # acquire/release when these are missing, but ManagedCondition calls
    # them directly) --------------------------------------------------------

    def _is_owned_by(self, mt: Optional[_MThread]) -> bool:
        return mt is not None and self.owner == mt.index


class ManagedCondition:
    """Condition variable under the cooperative scheduler. ``wait`` parks
    the thread (runnable again on notify, or — for timed waits — at the
    scheduler's discretion, modelling 'the timeout may fire at any
    moment')."""

    def __init__(self, sched: "_Scheduler", lock: ManagedLock):
        self._sched = sched
        self._lock = lock
        self._waiters: List[_MThread] = []

    def __enter__(self):
        return self._lock.acquire()

    def __exit__(self, *exc) -> None:
        self._lock.release()

    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)

    def release(self) -> None:
        self._lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._sched.cond_wait(self, timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        result = predicate()
        while not result:
            if not self.wait(timeout):
                return predicate()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._sched.cond_notify(self, n)

    def notify_all(self) -> None:
        self._sched.cond_notify(self, 1 << 30)

    notifyAll = notify_all


@dataclass
class RunResult:
    ok: bool
    message: str
    # the executed schedule: (step, runnable thread indices, chosen index,
    # chosen thread's parked label)
    trace: List[Tuple[int, Tuple[int, ...], int, str]]
    deviations: Dict[int, int]


class _Scheduler:
    """One cooperative execution of a scenario under forced deviations."""

    def __init__(
        self,
        deviations: Dict[int, int],
        rng: Optional[_random.Random] = None,
        deviate_prob: float = 0.0,
        max_steps: int = 20000,
    ):
        self._mu = _thread.allocate_lock()
        self._sched_gate = _Gate()
        self._threads: List[_MThread] = []
        self._by_ident: Dict[int, _MThread] = {}
        self._forced = dict(deviations)
        self._rng = rng
        self._deviate_prob = deviate_prob
        self._max_steps = max_steps
        self._sched_ident = _thread.get_ident()
        self.trace: List[Tuple[int, Tuple[int, ...], int, str]] = []
        self.effective_deviations: Dict[int, int] = {}
        self._installed: Optional[Tuple[Any, Any, Any]] = None
        self._prev_hook: Any = None
        self._abort = False
        self._closed = False
        self._lock_seq = 0  # per-run lock naming: replays label identically

    # -- factory patching ---------------------------------------------------

    def install(self) -> None:
        self._installed = (
            threading.Lock, threading.RLock, threading.Condition,
        )
        real_lock, real_rlock, real_cond = self._installed
        sched = self

        def lock_factory():
            if sched._is_scheduling_thread():
                return ManagedLock(sched, reentrant=False)
            return real_lock()

        def rlock_factory():
            if sched._is_scheduling_thread():
                return ManagedLock(sched, reentrant=True)
            return real_rlock()

        def cond_factory(lock=None):
            if isinstance(lock, ManagedLock):
                return ManagedCondition(sched, lock)
            if lock is None and sched._is_scheduling_thread():
                return ManagedCondition(
                    sched, ManagedLock(sched, reentrant=True)
                )
            return real_cond(lock)

        threading.Lock = lock_factory  # type: ignore[assignment]
        threading.RLock = rlock_factory  # type: ignore[assignment]
        threading.Condition = cond_factory  # type: ignore[assignment]
        self._prev_hook = yieldpoints.set_hook(self._on_yield_point)

    def uninstall(self) -> None:
        if self._installed is None:
            return
        threading.Lock, threading.RLock, threading.Condition = (  # type: ignore[assignment]
            self._installed
        )
        self._installed = None
        yieldpoints.set_hook(self._prev_hook)
        # OS thread idents are recycled: a LATER unrelated thread reusing a
        # dead scenario thread's ident must never be mistaken for managed
        self._closed = True
        self._by_ident.clear()

    def _is_scheduling_thread(self) -> bool:
        ident = _thread.get_ident()
        return ident == self._sched_ident or ident in self._by_ident

    def _current(self) -> Optional[_MThread]:
        return self._by_ident.get(_thread.get_ident())

    # -- spawning -----------------------------------------------------------

    def spawn(self, fn: Callable[[], None], name: str) -> _MThread:
        mt = _MThread(index=len(self._threads), name=name, fn=fn)
        self._threads.append(mt)
        _thread.start_new_thread(self._thread_main, (mt,))
        return mt

    def _thread_main(self, mt: _MThread) -> None:
        with self._mu:
            mt.ident = _thread.get_ident()
            self._by_ident[mt.ident] = mt
        mt.gate.wait()  # first grant
        try:
            if not self._abort:
                mt.fn()
        # oplint: disable=EXC001 — the catch IS the reporting channel: a
        # dying scenario thread becomes a FINDING (run_scenario renders
        # mt.exc), and _Aborted teardown unwinds must also land here
        except BaseException as e:
            mt.exc = e
        mt.state = _DONE
        self._sched_gate.open()

    # -- yield protocol (called from managed threads) -----------------------

    def _park(self, mt: _MThread, label: str) -> None:
        if self._abort:
            raise _Aborted()
        mt.last_label = label
        self._sched_gate.open()
        mt.gate.wait()
        if self._abort:
            raise _Aborted()

    def _on_yield_point(self, op: str, detail: str) -> None:
        mt = self._current()
        if mt is None:
            return  # scheduler/unmanaged thread: not schedulable
        self._park(mt, f"{op}({detail})" if detail else op)

    def lock_acquire(self, lock: ManagedLock, blocking: bool, timeout: float) -> bool:
        mt = self._current()
        if mt is None:
            # scheduler (setup/check) or foreign thread: take only if free
            with self._mu:
                if lock.owner is None or (
                    lock._reentrant and lock.owner == -1
                ):
                    lock.owner = -1  # the scheduler pseudo-index
                    lock.count += 1
                    return True
            if not blocking or timeout == 0:
                return False
            raise ExploreError(
                f"unmanaged thread would block on managed {lock.name} "
                f"(scenario code must not share managed locks with "
                f"background OS threads)"
            )
        if lock._reentrant and lock.owner == mt.index:
            lock.count += 1
            return True
        if self._abort:
            # teardown unwind: mutual exclusion is moot (one thread runs);
            # force-take so finally blocks can complete
            lock.owner = mt.index
            lock.count += 1
            return True
        timed = (not blocking) or timeout >= 0
        mt.wait_lock = lock
        mt.timed = timed
        self._park(mt, f"acquire:{lock.name}")
        mt.wait_lock = None
        if lock.owner is None:
            lock.owner = mt.index
            lock.count += 1
            return True
        return False  # timed/non-blocking attempt lost

    def lock_release(self, lock: ManagedLock) -> None:
        mt = self._current()
        holder = -1 if mt is None else mt.index
        if lock.owner != holder:
            if self._abort or self._closed:
                lock.owner, lock.count = None, 0  # best-effort teardown
                return
            raise RuntimeError(
                f"release of {lock.name} by non-owner "
                f"(owner={lock.owner}, releaser={holder})"
            )
        lock.count -= 1
        if lock.count == 0:
            lock.owner = None

    def cond_wait(self, cond: ManagedCondition, timeout: Optional[float]) -> bool:
        mt = self._current()
        lock = cond._lock
        if mt is not None and self._abort:
            return False  # teardown: report a spurious timeout and unwind
        if mt is None:
            # scheduler thread polling a managed condition: model the
            # timeout as already expired; an untimed wait can never be
            # satisfied (no managed thread will run again)
            if timeout is not None:
                return False
            raise ExploreError(
                "scheduler thread blocked on untimed managed Condition.wait"
            )
        if not lock._is_owned_by(mt):
            raise RuntimeError("cannot wait on un-acquired condition")
        saved = lock.count
        lock.count = 0
        lock.owner = None
        mt.wait_cond = cond
        mt.timed = timeout is not None
        mt.notified = False
        cond._waiters.append(mt)
        self._park(mt, "cond.wait" if timeout is None else "cond.wait(timed)")
        mt.wait_cond = None
        if mt in cond._waiters:
            cond._waiters.remove(mt)
        notified = mt.notified
        # re-acquire the lock cooperatively before returning
        while lock.owner not in (None, mt.index):
            mt.wait_lock = lock
            mt.timed = False
            self._park(mt, "cond.reacquire")
            mt.wait_lock = None
        lock.owner = mt.index
        lock.count = saved
        return notified

    def cond_notify(self, cond: ManagedCondition, n: int) -> None:
        with self._mu:
            hit = 0
            for waiter in cond._waiters:
                if not waiter.notified:
                    waiter.notified = True
                    hit += 1
                    if hit >= n:
                        break

    # -- the schedule loop (runs in the creating thread) --------------------

    def _is_runnable(self, t: _MThread) -> bool:
        if t.state == _DONE:
            return False
        if t.wait_lock is not None:
            # owner == t.index means a non-reentrant self-acquire: a REAL
            # deadlock, never runnable (reentrant re-acquire returns before
            # parking and cannot reach here)
            return t.timed or t.wait_lock.owner is None
        if t.wait_cond is not None:
            return t.notified or t.timed
        return True

    def run_all(self) -> None:
        """Schedule until every managed thread is done. Raises _Failure on
        deadlock; scenario exceptions are collected on the thread."""
        step = 0
        last: Optional[_MThread] = None
        while True:
            alive = [t for t in self._threads if t.state != _DONE]
            if not alive:
                return
            runnable = [t for t in alive if self._is_runnable(t)]
            if not runnable:
                waits = "; ".join(
                    f"t{t.index}({t.name}) at {t.last_label}" for t in alive
                )
                self._drain_abort()
                raise _Failure(f"DEADLOCK: no thread runnable — {waits}")
            if step >= self._max_steps:
                self._drain_abort()
                raise ExploreError(
                    f"step budget {self._max_steps} exhausted (a timed wait "
                    f"spinning on wall-clock time? bound scenario loops)"
                )
            default = last if last in runnable else runnable[0]
            chosen = default
            if step in self._forced:
                want = self._forced[step]
                by_index = {t.index: t for t in runnable}
                if want not in by_index:
                    # drain BEFORE raising, like the deadlock/step-budget
                    # paths: the parked scenario threads would otherwise
                    # leak blocked on their gates forever
                    self._drain_abort()
                    raise ExploreError(
                        f"schedule token does not apply: step {step} wants "
                        f"t{want}, runnable = "
                        f"{sorted(by_index)} (code or scenario changed?)"
                    )
                chosen = by_index[want]
            elif (
                self._rng is not None
                and len(runnable) > 1
                and self._rng.random() < self._deviate_prob
            ):
                chosen = runnable[self._rng.randrange(len(runnable))]
            self.trace.append(
                (
                    step,
                    tuple(t.index for t in runnable),
                    chosen.index,
                    chosen.last_label,
                )
            )
            if chosen is not default:
                self.effective_deviations[step] = chosen.index
            last = chosen
            chosen.gate.open()
            self._sched_gate.wait()
            step += 1

    def _drain_abort(self) -> None:
        """Tear down parked threads after a deadlock/step-budget stop:
        every grant now raises _Aborted at the thread's park point, so the
        OS threads actually exit instead of leaking blocked forever."""
        self._abort = True
        while True:
            alive = [t for t in self._threads if t.state != _DONE]
            if not alive:
                return
            alive[0].gate.open()
            self._sched_gate.wait()


class _Failure(Exception):
    """Internal: a scenario finding (invariant violation / deadlock)."""


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Scenario:
    """A small concurrent unit: ``build()`` returns (thread bodies, check).
    ``build`` runs UNDER the cooperative window, so locks/stores it
    constructs are managed; ``check`` runs on the scheduler thread after
    every body finished and raises AssertionError on violation."""

    name: str
    doc: str
    build: Callable[[], Tuple[List[Callable[[], None]], Callable[[], None]]]
    # True when the scenario is EXPECTED to have a reachable violation
    # (seeded-bug scenarios used to prove the explorer finds real bugs)
    seeded_bug: bool = False


class PlainKV:
    """The smallest possible store: a dict with labeled yield points on
    get/put — the two-writer get+update atomicity scenario rides this
    (ISSUE 5 acceptance)."""

    def __init__(self, data: Optional[Dict[str, Any]] = None):
        self._d = dict(data or {})

    def get(self, key: str) -> Any:
        yieldpoints.yield_point("kv.get", key)
        return self._d.get(key)

    def put(self, key: str, value: Any) -> None:
        yieldpoints.yield_point("kv.put", key)
        self._d[key] = value


def _scn_dict_rmw():
    """Two writers get+update a plain dict-backed counter with no guard:
    the classic atomicity violation. EXPECTED to fail under exploration —
    the seeded bug that proves the explorer finds real interleavings."""
    kv = PlainKV({"x": 0})

    def writer():
        v = kv.get("x")
        kv.put("x", v + 1)

    def check():
        got = kv._d["x"]
        assert got == 2, f"lost update: x == {got}, expected 2"

    return [writer, writer], check


def _scn_store_rmw_force():
    """Two writers do the RMW001 anti-pattern against a real ObjectStore —
    get, mutate, ``update(force=True)``: the force skips the rv check, so
    an adversarial schedule silently drops one increment. EXPECTED to
    fail; the runtime twin of oplint's RMW001/TERM001."""
    from mpi_operator_tpu.machinery.objects import Pod
    from mpi_operator_tpu.machinery.store import ObjectStore
    from mpi_operator_tpu.api.types import ObjectMeta

    store = ObjectStore()
    store.create(Pod(metadata=ObjectMeta(name="p", labels={"n": "0"})))

    def writer():
        cur = store.get("Pod", "default", "p")
        cur.metadata.labels["n"] = str(int(cur.metadata.labels["n"]) + 1)
        # oplint: disable=RMW001,TERM001 — deliberately the anti-pattern
        # both rules exist for: this scenario PROVES the force-PUT loses
        # updates by having the explorer find the schedule that drops one
        store.update(cur, force=True)

    def check():
        got = store.get("Pod", "default", "p").metadata.labels["n"]
        assert got == "2", f"lost update: n == {got!r}, expected '2'"

    return [writer, writer], check


def _scn_store_optimistic():
    """The blessed form of the same write: ``optimistic_update`` re-reads
    on Conflict. Must survive EVERY schedule in budget — the proof the
    sanctioned idiom is actually sound, not just lint-blessed."""
    from mpi_operator_tpu.machinery.objects import Pod
    from mpi_operator_tpu.machinery.store import ObjectStore, optimistic_update
    from mpi_operator_tpu.api.types import ObjectMeta

    store = ObjectStore()
    store.create(Pod(metadata=ObjectMeta(name="p", labels={"n": "0"})))

    def writer():
        def bump(cur):
            cur.metadata.labels["n"] = str(int(cur.metadata.labels["n"]) + 1)
            return True

        optimistic_update(store, "Pod", "default", "p", bump)

    def check():
        got = store.get("Pod", "default", "p").metadata.labels["n"]
        assert got == "2", f"optimistic_update lost a write: n == {got!r}"

    return [writer, writer], check


def _scn_store_patch():
    """Two writers merge-patch DISJOINT status fields concurrently; the
    server-side patch is atomic under the store lock, so both fields must
    survive every schedule (the PR 2 write-path contract)."""
    from mpi_operator_tpu.machinery.objects import Pod
    from mpi_operator_tpu.machinery.store import ObjectStore
    from mpi_operator_tpu.api.types import ObjectMeta

    store = ObjectStore()
    store.create(Pod(metadata=ObjectMeta(name="p")))

    def patch_reason():
        # oplint: disable=UID001 — single-incarnation scenario: no
        # recreation can happen between build and check, and the point is
        # the MERGE atomicity of two unpinned writers
        store.patch("Pod", "default", "p",
                    {"status": {"reason": "Evicted"}}, subresource="status")

    def patch_message():
        # oplint: disable=UID001 — same single-incarnation scenario
        store.patch("Pod", "default", "p",
                    {"status": {"message": "drained"}}, subresource="status")

    def check():
        got = store.get("Pod", "default", "p")
        assert got.status.reason == "Evicted" and got.status.message == "drained", (
            f"concurrent patches clobbered each other: "
            f"reason={got.status.reason!r} message={got.status.message!r}"
        )

    return [patch_reason, patch_message], check


def _scn_workqueue():
    """Producers racing a consumer through RateLimitingQueue: every
    distinct key must come out (dedup may collapse, never lose), and the
    consumer's untimed get() must never deadlock — a lost cond wakeup
    shows up here as a DEADLOCK finding."""
    from mpi_operator_tpu.machinery.workqueue import RateLimitingQueue

    q = RateLimitingQueue()
    all_keys = {"k0", "k1", "k2", "k3"}
    seen: set = set()

    def producer_a():
        for k in ("k0", "k1", "k2"):
            q.add(k)

    def producer_b():
        for k in ("k1", "k2", "k3"):
            q.add(k)

    def consumer():
        while True:
            # oplint: disable=BLK001 — under the cooperative scheduler an
            # unbounded get is exactly right: a lost wakeup surfaces as a
            # DEADLOCK finding instead of hanging (and shut_down unblocks
            # the normal path); a timed get would wall-clock-spin instead
            key = q.get()
            if key is None:
                return
            seen.add(key)
            q.done(key)
            if seen >= all_keys:
                q.shut_down()
                return

    def check():
        assert seen >= all_keys, f"workqueue lost keys: got only {sorted(seen)}"

    return [producer_a, producer_b, consumer], check


def _scn_cache_rv_guard():
    """A Lister fed MODIFIED events out of order by two pump threads while
    a reader lists: the rv guard must make the newest version win under
    every interleaving (the informer staleness contract)."""
    from mpi_operator_tpu.machinery.cache import Lister
    from mpi_operator_tpu.machinery.objects import Pod
    from mpi_operator_tpu.machinery.store import MODIFIED
    from mpi_operator_tpu.api.types import ObjectMeta

    lister = Lister("Pod", index_labels=())

    def _pod(rv: int) -> Any:
        p = Pod(metadata=ObjectMeta(name="p", labels={"v": str(rv)}))
        p.metadata.resource_version = rv
        return p

    def pump_new():
        lister.apply(MODIFIED, _pod(2))
        lister.apply(MODIFIED, _pod(3))

    def pump_stale():
        lister.apply(MODIFIED, _pod(1))

    def reader():
        lister.list()

    def check():
        got = lister.get("default", "p")
        assert got.metadata.resource_version == 3, (
            f"stale event regressed the cache to rv "
            f"{got.metadata.resource_version}"
        )

    return [pump_new, pump_stale, reader], check


SCENARIOS: Dict[str, Scenario] = {
    s.name: s
    for s in (
        Scenario("dict-rmw", _scn_dict_rmw.__doc__ or "", _scn_dict_rmw,
                 seeded_bug=True),
        Scenario("store-rmw-force", _scn_store_rmw_force.__doc__ or "",
                 _scn_store_rmw_force, seeded_bug=True),
        Scenario("store-optimistic", _scn_store_optimistic.__doc__ or "",
                 _scn_store_optimistic),
        Scenario("store-patch", _scn_store_patch.__doc__ or "",
                 _scn_store_patch),
        Scenario("workqueue", _scn_workqueue.__doc__ or "", _scn_workqueue),
        Scenario("cache-rv-guard", _scn_cache_rv_guard.__doc__ or "",
                 _scn_cache_rv_guard),
    )
}


# ---------------------------------------------------------------------------
# running + exploring
# ---------------------------------------------------------------------------


def encode_token(scenario: str, deviations: Dict[int, int]) -> str:
    body = ",".join(f"{s}={t}" for s, t in sorted(deviations.items())) or "-"
    return f"{TOKEN_VERSION}:{scenario}:{body}"


def decode_token(token: str) -> Tuple[str, Dict[int, int]]:
    try:
        version, scenario, body = token.split(":", 2)
        if version != TOKEN_VERSION:
            raise ValueError(f"unknown token version {version!r}")
        if scenario not in SCENARIOS:
            raise ValueError(f"unknown scenario {scenario!r}")
        dev: Dict[int, int] = {}
        if body != "-":
            for part in body.split(","):
                s, t = part.split("=")
                dev[int(s)] = int(t)
        return scenario, dev
    except ValueError as e:
        raise ExploreError(f"bad schedule token {token!r}: {e}") from None


def run_scenario(
    name: str,
    deviations: Optional[Dict[int, int]] = None,
    *,
    rng: Optional[_random.Random] = None,
    deviate_prob: float = 0.0,
    max_steps: int = 20000,
) -> RunResult:
    """One cooperative execution. Deterministic given (scenario code,
    deviations, rng state): the trace, the failure — everything."""
    scenario = SCENARIOS[name]
    sched = _Scheduler(deviations or {}, rng, deviate_prob, max_steps)
    sched.install()
    try:
        bodies, check = scenario.build()
        for i, fn in enumerate(bodies):
            sched.spawn(fn, getattr(fn, "__name__", f"t{i}"))
        failure: Optional[str] = None
        try:
            sched.run_all()
            unreached = [s for s in sched._forced if s >= len(sched.trace)]
            if unreached:
                raise ExploreError(
                    f"schedule token does not apply: step(s) "
                    f"{sorted(unreached)} never reached (the run ended at "
                    f"step {len(sched.trace)}; code or scenario changed?)"
                )
        except _Failure as f:
            failure = str(f)
        if failure is None:
            for t in sched._threads:
                if t.exc is not None and not isinstance(t.exc, _Aborted):
                    failure = (
                        f"t{t.index}({t.name}) died: "
                        f"{type(t.exc).__name__}: {t.exc}"
                    )
                    break
        if failure is None:
            try:
                check()
            except AssertionError as e:
                failure = f"invariant violated: {e}"
        dev = dict(sched.effective_deviations)
        if failure is not None:
            token = encode_token(name, dev)
            return RunResult(False, f"{failure}\n  schedule token: {token}",
                             sched.trace, dev)
        return RunResult(True, "ok", sched.trace, dev)
    finally:
        sched.uninstall()


@dataclass
class ExploreReport:
    scenario: str
    ok: bool
    runs: int
    schedules_seen: int
    failure: Optional[RunResult] = None

    def render(self) -> str:
        if self.ok:
            return (
                f"explore {self.scenario}: ok — {self.runs} run(s), "
                f"{self.schedules_seen} distinct schedule(s), no violation"
            )
        return (
            f"explore {self.scenario}: FAILED after {self.runs} run(s)\n"
            f"  {self.failure.message}"
        )


def explore(
    name: str,
    budget: ExploreBudget = FAST_BUDGET,
    *,
    mode: str = "systematic",
    seed: int = 0,
) -> ExploreReport:
    """Explore a scenario's schedules within budget. ``systematic``
    enumerates deviation sets up to the preemption bound (DFS over
    observed choice points, CHESS-style); ``random`` samples seeded
    deviations per run. Returns on the FIRST failing schedule — its token
    replays the exact interleaving."""
    if name not in SCENARIOS:
        raise ExploreError(
            f"unknown scenario {name!r} (have: {', '.join(sorted(SCENARIOS))})"
        )
    runs = 0
    if mode == "random":
        rng = _random.Random(seed)
        while runs < budget.max_runs:
            result = run_scenario(
                name, rng=rng, deviate_prob=0.35, max_steps=budget.max_steps
            )
            runs += 1
            if not result.ok:
                # re-encode as a forced run so the token is authoritative
                return ExploreReport(name, False, runs, runs, result)
        return ExploreReport(name, True, runs, runs)
    if mode != "systematic":
        raise ExploreError(f"unknown mode {mode!r} (systematic|random)")

    tried: set = set()
    # DFS frontier of deviation maps; {} = the unperturbed default schedule
    frontier: List[Dict[int, int]] = [{}]
    while frontier and runs < budget.max_runs:
        dev = frontier.pop()
        key = tuple(sorted(dev.items()))
        if key in tried:
            continue
        tried.add(key)
        result = run_scenario(name, dev, max_steps=budget.max_steps)
        runs += 1
        if not result.ok:
            return ExploreReport(name, False, runs, len(tried), result)
        if len(dev) >= budget.max_preemptions:
            continue
        start = (max(dev) + 1) if dev else 0
        # append deepest-first so pop() explores the EARLIEST new choice
        # point next — low preemption points find RMW windows fastest
        for step, runnable, chosen, _label in reversed(result.trace):
            if step < start:
                break
            for alt in runnable:
                if alt != chosen:
                    frontier.append({**dev, step: alt})
    return ExploreReport(name, True, runs, len(tried))


def replay(token: str, *, max_steps: int = 20000) -> RunResult:
    """Re-execute the exact interleaving a token encodes."""
    name, dev = decode_token(token)
    return run_scenario(name, dev, max_steps=max_steps)


def self_test() -> List[str]:
    """The explorer's own acceptance gate (ISSUE 5): the seeded two-writer
    atomicity violation is found deterministically, its token replays to
    the IDENTICAL failure twice, and a clean scenario stays clean. Returns
    failure strings (empty = pass)."""
    failures: List[str] = []
    report = explore("dict-rmw", ExploreBudget(max_runs=40, max_preemptions=1))
    if report.ok:
        failures.append("seeded dict-rmw atomicity violation was NOT found")
        return failures
    token = encode_token("dict-rmw", report.failure.deviations)
    first = replay(token)
    second = replay(token)
    if first.ok or second.ok:
        failures.append(f"token {token} did not replay to a failure")
    elif first.message != second.message or first.trace != second.trace:
        failures.append(f"token {token} replays diverged (nondeterminism)")
    clean = explore(
        "store-patch", ExploreBudget(max_runs=40, max_preemptions=1)
    )
    if not clean.ok:
        failures.append(
            "store-patch should survive every schedule: " + clean.failure.message
        )
    return failures
