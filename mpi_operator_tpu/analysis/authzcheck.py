"""authzcheck — the store's security plane diffed against ONE declaration.

The reference operator's layer 4 materializes RBAC and scoped service
accounts so launcher pods can only touch their own workers; our store
grew the same posture organically — four token tiers (admin/read/node/
peer), a status-subresource freeze, per-key denials (cordon,
conditions), uid pinning, namespace quota — but every rule lived ad hoc
in its handler, and four separate review passes (PRs 2, 10, 12, 13)
each found a tier bug by hand. This module gives authorization what the
store seam has from storecheck: a single declarative source of truth
(``analysis/authz_policy.json``: every (route-pattern, tier,
scope-variant) → expected outcome), loaded FAIL-CLOSED, and a probe
harness that boots a REAL fleet — a tokened StoreServer (memory- or
sqlite-backed) with a replication seam, an unauthenticated open-server
variant, a non-leader replica, and the OpsServer monitoring port — then
fires a real HTTP request for every matrix cell and diffs the observed
status code + typed error against the declaration.

Route coverage is introspected from the live router
(``http_store.servable_routes()``), so a servable route ABSENT from the
matrix is itself a finding: new endpoints must declare posture before
they ship. The client-side peer table (``replica_wire.PEER_ROUTES``) is
diffed against the server's for mirror drift. The OpsServer probe also
wire-captures /metrics and scans the exposition body for fleet secrets
and secret-named label values (SEC001's runtime twin).

Every diff carries a deterministic ``v1:authz:<route>:<tier>:<variant>``
token that ``--replay`` re-probes exactly (fresh fleet, one cell).
``self_test()`` is the detector-of-the-detector bar: the full matrix
probes clean on BOTH backends with identical denied-cell codes, each of
the six seeded mutants (the bug classes those review passes kept
finding) is caught on its expected token with a twice-identical replay,
and an injected undeclared route fails closed as a finding.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib import error as urlerror
from urllib import request as urlrequest

__all__ = [
    "AuthzConfigError",
    "Finding",
    "Fleet",
    "MUTANTS",
    "Policy",
    "ProbeReport",
    "TIERS",
    "coverage_findings",
    "encode_token",
    "load_policy",
    "make_fleet",
    "parse_token",
    "probe",
    "replay",
    "scan_exposition",
    "self_test",
]

DEFAULT_POLICY_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "authz_policy.json"
)

# probe order is the tier lattice, weakest credential first
TIERS = ("anon", "garbage", "read", "node", "peer", "admin")

# fixed fleet credentials: the values are arbitrary but STABLE so replay
# tokens probe the identical fleet; the secret-scan below asserts none of
# them ever appears in a /metrics exposition body
# oplint: disable=SEC001 — test-fleet credentials, minted fresh per probe
_FLEET_TOKENS = {
    "admin": "authz-adm1n-t0k3n",
    "read": "authz-read-t0k3n",
    "node": "authz-agent-t0k3n",
    "peer": "authz-p33r-t0k3n",
    "garbage": "authz-garbage-t0k3n",
    "anon": None,
}

NODE_NAME = "n1"
OTHER_NODE = "n2"
WL_NS = "wl"
QUOTA_NS = "quota-ns"

_TOKEN_PREFIX = "v1:authz:"


class AuthzConfigError(ValueError):
    """authz_policy.json (or a replay token) failed validation — the
    loader refuses rather than guessing: an authorization matrix that
    silently dropped a tier or route would certify a hole as clean."""


# ---------------------------------------------------------------------------
# outcome grammar
# ---------------------------------------------------------------------------

_OUTCOME_RE = re.compile(r"^(?:allow|(?:deny|pass):[1-5][0-9]{2}:[A-Za-z]+)$")


@dataclass(frozen=True)
class Outcome:
    """One declared cell outcome. ``kind`` is 'allow' (authorized, 200),
    'deny' (the authorization plane refuses with a typed error) or
    'pass' (authz ADMITS the request; the handler's in-band typed
    outcome — AlreadyExists on re-registration, NotFound on a raced
    delete, NotLeader on a follower — is the declared posture). deny and
    pass verify identically on the wire; the split documents WHERE the
    answer comes from."""

    raw: str
    kind: str
    status: int
    error: Optional[str]

    @staticmethod
    def parse(raw: Any, where: str) -> "Outcome":
        if not isinstance(raw, str) or not _OUTCOME_RE.match(raw):
            raise AuthzConfigError(
                f"{where}: outcome {raw!r} does not match the grammar "
                f"'allow' | 'deny:<code>:<Error>' | 'pass:<code>:<Error>'"
            )
        if raw == "allow":
            return Outcome(raw=raw, kind="allow", status=200, error=None)
        kind, code, err = raw.split(":")
        return Outcome(raw=raw, kind=kind, status=int(code), error=err)

    def matches(self, status: int, err: Optional[str]) -> bool:
        if self.kind == "allow":
            return status == 200 and err is None
        return status == self.status and err == self.error


# ---------------------------------------------------------------------------
# the declared matrix, loaded fail-closed
# ---------------------------------------------------------------------------

_TOP_KEYS = {"_comment", "version", "semantics", "routes", "ops_server"}
_SEMANTIC_KEYS = {
    "_comment", "missing_token", "invalid_token", "wrong_tier",
    "out_of_scope", "stale_rv_write", "not_leader",
}

# scope variants per (route, tier); every other cell has exactly
# ("default",). The loader enforces EXACT agreement between this table
# and the policy file, so a variant declared without a builder (or built
# without a declaration) is a config error, not a silent skip.
_EXTRA_VARIANTS: Dict[Tuple[str, str], Tuple[str, ...]] = {
    ("GET /v1/objects/{kind}/{ns}/{name}", "anon"):
        ("default", "open_server"),
    ("POST /v1/objects", "anon"): ("default", "open_server"),
    ("POST /v1/objects", "node"):
        ("own_node_register", "own_node_wrong_namespace",
         "other_kind_create"),
    ("POST /v1/objects", "admin"): ("default", "quota_exceeded"),
    ("PUT /v1/objects/{kind}/{ns}/{name}", "anon"):
        ("default", "open_server"),
    ("PUT /v1/objects/{kind}/{ns}/{name}", "node"):
        ("own_node_heartbeat", "other_node", "cordon_flip",
         "conditions_change", "stale_rv", "force_update", "own_pod",
         "other_pod", "pod_relabel", "pod_reuid"),
    ("PUT /v1/objects/{kind}/{ns}/{name}", "admin"):
        ("default", "not_leader"),
    ("PATCH /v1/objects/{kind}/{ns}/{name}/{subresource}", "node"):
        ("own_node_status", "cordon_key", "conditions_key",
         "spec_subresource", "own_pod_status", "other_pod_status",
         "absent_pod_status", "uid_precondition_overwritten"),
    ("POST /v1/patch-batch", "node"):
        ("own_status_batch", "item_crosses_tier", "spec_item"),
    ("POST /v1/replica/append-entries", "anon"):
        ("default", "open_server"),
}


def variants_for(route: str, tier: str) -> Tuple[str, ...]:
    return _EXTRA_VARIANTS.get((route, tier), ("default",))


@dataclass(frozen=True)
class Policy:
    version: int
    semantics: Dict[str, str]
    # route → tier → variant → Outcome
    routes: Dict[str, Dict[str, Dict[str, Outcome]]]
    ops_server: Dict[str, Outcome]


def _refuse_dups(pairs):
    d: Dict[str, Any] = {}
    for k, v in pairs:
        if k in d:
            raise AuthzConfigError(f"duplicate key {k!r} in authz policy")
        d[k] = v
    return d


def servable_routes() -> List[str]:
    """The live router's route table (re-exported so callers and tests
    need only this module)."""
    from mpi_operator_tpu.machinery.http_store import (
        servable_routes as _live_routes,
    )

    return _live_routes()


def load_policy(
    path: Optional[str] = None, *, servable: Optional[List[str]] = None
) -> Policy:
    """Parse + validate the matrix, refusing anything it cannot fully
    account for: unknown top-level keys, a version this checker does not
    speak, unknown/missing tiers, unknown/missing scope variants, bad
    outcome grammar, duplicate keys, and policy routes the live router
    cannot serve. (The INVERSE gap — servable but undeclared — is a
    probe finding via coverage_findings, not a load error: the policy
    file must stay loadable so the finding can be reported.)"""
    path = path or DEFAULT_POLICY_PATH
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        raise AuthzConfigError(f"cannot read authz policy {path}: {e}")
    try:
        doc = json.loads(text, object_pairs_hook=_refuse_dups)
    except json.JSONDecodeError as e:
        raise AuthzConfigError(f"authz policy {path} is not valid JSON: {e}")
    if not isinstance(doc, dict):
        raise AuthzConfigError("authz policy must be a JSON object")
    unknown = set(doc) - _TOP_KEYS
    if unknown:
        raise AuthzConfigError(
            f"unknown top-level key(s) {sorted(unknown)} in authz policy"
        )
    missing = _TOP_KEYS - {"_comment"} - set(doc)
    if missing:
        raise AuthzConfigError(
            f"authz policy is missing top-level key(s) {sorted(missing)}"
        )
    if doc["version"] != 1:
        raise AuthzConfigError(
            f"authz policy version {doc['version']!r} is not 1"
        )
    semantics = doc["semantics"]
    if not isinstance(semantics, dict):
        raise AuthzConfigError("'semantics' must be an object")
    bad = set(semantics) - _SEMANTIC_KEYS
    if bad:
        raise AuthzConfigError(f"unknown semantics key(s) {sorted(bad)}")
    for k, v in semantics.items():
        if k != "_comment":
            Outcome.parse(v, f"semantics.{k}")
    raw_routes = doc["routes"]
    if not isinstance(raw_routes, dict) or not raw_routes:
        raise AuthzConfigError("'routes' must be a non-empty object")
    live = list(servable if servable is not None else servable_routes())
    routes: Dict[str, Dict[str, Dict[str, Outcome]]] = {}
    for route, cells in raw_routes.items():
        if route not in live:
            raise AuthzConfigError(
                f"policy declares route {route!r} but the live router "
                f"does not serve it (stale entry, or a typo that would "
                f"leave the real route unprobed)"
            )
        if not isinstance(cells, dict):
            raise AuthzConfigError(f"route {route!r}: cells must be an object")
        tier_keys = set(cells) - {"_comment"}
        if tier_keys - set(TIERS):
            raise AuthzConfigError(
                f"route {route!r}: unknown tier(s) "
                f"{sorted(tier_keys - set(TIERS))}"
            )
        if set(TIERS) - tier_keys:
            raise AuthzConfigError(
                f"route {route!r}: missing tier(s) "
                f"{sorted(set(TIERS) - tier_keys)} — every tier must "
                f"declare a posture (fail closed, no implicit allow)"
            )
        routes[route] = {}
        for tier in TIERS:
            raw_cell = cells[tier]
            expected = set(variants_for(route, tier))
            if isinstance(raw_cell, str):
                declared = {"default": raw_cell}
            elif isinstance(raw_cell, dict):
                declared = dict(raw_cell)
            else:
                raise AuthzConfigError(
                    f"route {route!r} tier {tier!r}: cell must be an "
                    f"outcome string or a variant object"
                )
            if set(declared) != expected:
                raise AuthzConfigError(
                    f"route {route!r} tier {tier!r}: declared variants "
                    f"{sorted(declared)} != probeable variants "
                    f"{sorted(expected)}"
                )
            routes[route][tier] = {
                variant: Outcome.parse(
                    raw, f"route {route!r} tier {tier!r} variant {variant!r}"
                )
                for variant, raw in declared.items()
            }
    raw_ops = doc["ops_server"]
    if not isinstance(raw_ops, dict):
        raise AuthzConfigError("'ops_server' must be an object")
    ops_keys = set(raw_ops) - {"_comment"}
    if ops_keys != {"GET /healthz", "GET /metrics"}:
        raise AuthzConfigError(
            f"ops_server must declare exactly GET /healthz and "
            f"GET /metrics, got {sorted(ops_keys)}"
        )
    ops = {
        r: Outcome.parse(raw_ops[r], f"ops_server {r!r}") for r in ops_keys
    }
    return Policy(version=1, semantics=dict(semantics), routes=routes,
                  ops_server=ops)


# ---------------------------------------------------------------------------
# finding tokens
# ---------------------------------------------------------------------------


def encode_token(route: str, tier: str, variant: str) -> str:
    return f"{_TOKEN_PREFIX}{route}:{tier}:{variant}"


def parse_token(token: str) -> Tuple[str, str, str]:
    """``v1:authz:<route>:<tier>:<variant>`` → (route, tier, variant).
    The route itself contains a space but never a colon, so the tail
    splits unambiguously right-to-left."""
    if not token.startswith(_TOKEN_PREFIX):
        raise AuthzConfigError(
            f"replay token {token!r} does not start with {_TOKEN_PREFIX!r}"
        )
    rest = token[len(_TOKEN_PREFIX):]
    parts = rest.rsplit(":", 2)
    if len(parts) != 3 or not all(parts) or " " not in parts[0]:
        raise AuthzConfigError(
            f"replay token {token!r} is not "
            f"'{_TOKEN_PREFIX}<METHOD /route>:<tier>:<variant>'"
        )
    return parts[0], parts[1], parts[2]


# ---------------------------------------------------------------------------
# the real fleet
# ---------------------------------------------------------------------------


class _ReplicaSeamStub:
    """Wraps a real backing store with a stub replication seam so the
    main server can be constructed with a peer token (StoreServer
    refuses a peer tier that routes nowhere). The peer cells only probe
    AUTHORIZATION — the RPCs land here and return inert acks; the real
    protocol has its own checkers (crash --replica, fuzz replica)."""

    def __init__(self, inner: Any):
        self._inner = inner

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def request_vote(self, *args: Any) -> Dict[str, Any]:
        return {"granted": False, "stub": True}

    def append_entries(self, *args: Any) -> Dict[str, Any]:
        return {"ok": True, "stub": True}

    def fetch_entries(self, *args: Any) -> Dict[str, Any]:
        return {"entries": [], "stub": True}

    def install_snapshot(self, *args: Any) -> Dict[str, Any]:
        return {"ok": True, "stub": True}

    def snapshot_chunk(self, *args: Any) -> Dict[str, Any]:
        return {"ok": True, "stub": True}

    def snapshot_done(self, *args: Any) -> Dict[str, Any]:
        return {"ok": True, "stub": True}


class _NotLeaderStub:
    """Wraps a backing store as a non-leader replica: every mutation
    bounces NotLeader with a leader hint, reads pass through — the 421
    posture cell probes the wire mapping without electing anything."""

    LEADER_HINT = "http://leader.invalid:8475"

    def __init__(self, inner: Any):
        self._inner = inner

    def __getattr__(self, name: str) -> Any:
        if name in ("create", "update", "delete", "patch", "patch_batch"):
            from mpi_operator_tpu.machinery.store import NotLeader

            def bounce(*args: Any, **kwargs: Any) -> Any:
                raise NotLeader(
                    "this replica is a follower; mutations go to the "
                    "leased leader", leader=self.LEADER_HINT,
                )

            return bounce
        return getattr(self._inner, name)


@dataclass
class Fleet:
    """One booted probe fleet: the tokened main server, the
    unauthenticated open server, the non-leader follower, the OpsServer
    monitoring port — plus direct handles on the backings so builders
    can read current rv/uid state at fire time (order-robust)."""

    backend: str
    main: Any
    open: Any
    follower: Any
    ops: Any
    main_backing: Any
    open_backing: Any
    follower_backing: Any
    _cleanups: List[Callable[[], None]] = field(default_factory=list)

    def url(self, server_key: str) -> str:
        if server_key == "ops":
            return f"http://127.0.0.1:{self.ops.port}"
        return {"main": self.main, "open": self.open,
                "follower": self.follower}[server_key].url

    def close(self) -> None:
        for srv in (self.main, self.open, self.follower):
            try:
                srv.stop()
            except Exception:  # oplint: disable=EXC001 — teardown best-effort
                pass
        try:
            self.ops.stop()
        except Exception:  # oplint: disable=EXC001 — teardown best-effort
            pass
        for fn in self._cleanups:
            fn()


def _mk_backing(backend: str) -> Tuple[Any, Callable[[], None]]:
    if backend == "memory":
        from mpi_operator_tpu.machinery.store import ObjectStore

        return ObjectStore(), lambda: None
    if backend == "sqlite":
        from mpi_operator_tpu.machinery.sqlite_store import SqliteStore

        d = tempfile.mkdtemp(prefix="authzcheck-")
        s = SqliteStore(os.path.join(d, "authz.db"), poll_interval=0.01)

        def teardown() -> None:
            s.close()
            shutil.rmtree(d, ignore_errors=True)

        return s, teardown
    raise AuthzConfigError(f"unknown backend {backend!r}")


def _seed_main(backing: Any) -> None:
    from mpi_operator_tpu.api.types import ObjectMeta
    from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE, Node, Pod

    for name in (NODE_NAME, OTHER_NODE):
        backing.create(
            Node(metadata=ObjectMeta(name=name, namespace=NODE_NAMESPACE))
        )
    for name, bound_to in (
        ("p-own", NODE_NAME), ("p-other", OTHER_NODE), ("p-uid", NODE_NAME),
        ("p-del", ""), ("p-admin", ""),
    ):
        created = backing.create(
            Pod(metadata=ObjectMeta(name=name, namespace=WL_NS))
        )
        if bound_to:
            created.spec.node_name = bound_to
            # binding a fresh seed pod before the servers boot — no
            # concurrent writer exists for force to stomp
            backing.update(created, force=True)  # oplint: disable=TERM001


def make_fleet(backend: str = "memory") -> Fleet:
    from mpi_operator_tpu.api.types import ObjectMeta
    from mpi_operator_tpu.machinery.fairqueue import NamespaceQuota
    from mpi_operator_tpu.machinery.http_store import StoreServer
    from mpi_operator_tpu.machinery.objects import Pod
    from mpi_operator_tpu.machinery.store import ObjectStore
    from mpi_operator_tpu.opshell.server import OpsServer

    inner, cleanup = _mk_backing(backend)
    main_backing = _ReplicaSeamStub(inner)
    _seed_main(main_backing)
    main = StoreServer(
        main_backing, "127.0.0.1", 0,
        token=_FLEET_TOKENS["admin"],
        read_token=_FLEET_TOKENS["read"],
        auth_reads=True,
        agent_tokens={_FLEET_TOKENS["node"]: NODE_NAME},
        peer_token=_FLEET_TOKENS["peer"],
        quota=NamespaceQuota({QUOTA_NS: {"max_jobs": 0}}),
    ).start()
    open_backing = ObjectStore()
    open_backing.create(Pod(metadata=ObjectMeta(name="p-open",
                                                namespace=WL_NS)))
    open_srv = StoreServer(open_backing, "127.0.0.1", 0).start()
    follower_backing = _NotLeaderStub(ObjectStore())
    follower_backing._inner.create(
        Pod(metadata=ObjectMeta(name="p-own", namespace=WL_NS))
    )
    follower = StoreServer(
        follower_backing, "127.0.0.1", 0, token=_FLEET_TOKENS["admin"],
    ).start()
    ops = OpsServer(port=0)
    ops.start()
    return Fleet(
        backend=backend, main=main, open=open_srv, follower=follower,
        ops=ops, main_backing=main_backing, open_backing=open_backing,
        follower_backing=follower_backing, _cleanups=[cleanup],
    )


# ---------------------------------------------------------------------------
# cell builders: (route, tier, variant) → one concrete wire request
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Probe:
    server: str  # main | open | follower
    method: str
    path: str
    body: Optional[Dict[str, Any]]
    bearer: Optional[str]


def _enc(obj: Any) -> Dict[str, Any]:
    from mpi_operator_tpu.machinery.serialize import encode

    return encode(obj)


def _current(fleet: Fleet, server: str, kind: str, ns: str,
             name: str) -> Dict[str, Any]:
    backing = {"main": fleet.main_backing, "open": fleet.open_backing,
               "follower": fleet.follower_backing}[server]
    return _enc(backing.get(kind, ns, name))


def build_probe(fleet: Fleet, route: str, tier: str, variant: str) -> Probe:
    """The one concrete request a cell fires. Builders read CURRENT
    backing state (rv, uid, bindings) at fire time, so cells stay
    correct regardless of what earlier allow-cells mutated."""
    from mpi_operator_tpu.api.types import ObjectMeta, TPUJob
    from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE, Node, Pod

    method, path = route.split(" ", 1)
    server = "main"
    bearer = _FLEET_TOKENS[tier]
    if variant == "open_server":
        server, bearer = "open", None
    if variant == "not_leader":
        server = "follower"
    body: Optional[Dict[str, Any]] = None

    if path.startswith("/v1/replica/") and method == "POST":
        return Probe(server, method, path, {"src": "authz-probe", "args": []},
                     bearer)
    if route in ("GET /healthz", "GET /v1/replica/status", "GET /v1/watch"):
        return Probe(server, method, path, None, bearer)
    if route == "GET /v1/objects/{kind}":
        return Probe(server, method, "/v1/objects/Pod", None, bearer)
    if route == "GET /v1/objects/{kind}/{ns}/{name}":
        target = "p-open" if server == "open" else "p-own"
        return Probe(server, method, f"/v1/objects/Pod/{WL_NS}/{target}",
                     None, bearer)
    if route == "POST /v1/objects":
        if variant == "own_node_register":
            node = Node(metadata=ObjectMeta(name=NODE_NAME,
                                            namespace=NODE_NAMESPACE))
            body = {"kind": "Node", "object": _enc(node)}
        elif variant == "own_node_wrong_namespace":
            node = Node(metadata=ObjectMeta(name=NODE_NAME, namespace=WL_NS))
            body = {"kind": "Node", "object": _enc(node)}
        elif variant == "quota_exceeded":
            job = TPUJob(metadata=ObjectMeta(name="probe-quota",
                                             namespace=QUOTA_NS))
            body = {"kind": "TPUJob", "object": _enc(job)}
        else:
            pod = Pod(metadata=ObjectMeta(name=f"probe-{tier}-{variant}",
                                          namespace=WL_NS))
            body = {"kind": "Pod", "object": _enc(pod)}
        return Probe(server, method, "/v1/objects", body, bearer)
    if route == "PUT /v1/objects/{kind}/{ns}/{name}":
        return _build_put(fleet, server, tier, variant, bearer)
    if route == "DELETE /v1/objects/{kind}/{ns}/{name}":
        return Probe(server, method, f"/v1/objects/Pod/{WL_NS}/p-del",
                     None, bearer)
    if route == "PATCH /v1/objects/{kind}/{ns}/{name}":
        target = "p-admin" if tier == "admin" else "p-own"
        return Probe(server, method, f"/v1/objects/Pod/{WL_NS}/{target}",
                     {"patch": {"status": {"message": "authz-probe"}}},
                     bearer)
    if route == "PATCH /v1/objects/{kind}/{ns}/{name}/{subresource}":
        return _build_subresource_patch(fleet, server, tier, variant, bearer)
    if route == "POST /v1/patch-batch":
        return _build_batch(fleet, server, tier, variant, bearer)
    raise AuthzConfigError(f"no builder for route {route!r}")


def _build_put(fleet: Fleet, server: str, tier: str, variant: str,
               bearer: Optional[str]) -> Probe:
    from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE

    method = "PUT"
    if variant in ("own_node_heartbeat", "cordon_flip", "conditions_change",
                   "stale_rv", "force_update"):
        node = _current(fleet, server, "Node", NODE_NAMESPACE, NODE_NAME)
        if variant == "own_node_heartbeat":
            node["status"]["last_heartbeat"] = 123.0
        elif variant == "cordon_flip":
            node["status"]["unschedulable"] = (
                not node["status"].get("unschedulable", False)
            )
        elif variant == "conditions_change":
            node["status"]["conditions"] = [
                {"type": "Draining", "status": "True"}
            ]
        elif variant == "stale_rv":
            node["metadata"]["resource_version"] += 999
        suffix = "?force=1" if variant == "force_update" else ""
        return Probe(server, method,
                     f"/v1/objects/Node/{NODE_NAMESPACE}/{NODE_NAME}{suffix}",
                     {"object": node}, bearer)
    if variant == "other_node":
        node = _current(fleet, server, "Node", NODE_NAMESPACE, OTHER_NODE)
        return Probe(server, method,
                     f"/v1/objects/Node/{NODE_NAMESPACE}/{OTHER_NODE}",
                     {"object": node}, bearer)
    if variant in ("own_pod", "other_pod", "pod_relabel", "pod_reuid"):
        name = "p-other" if variant == "other_pod" else "p-own"
        pod = _current(fleet, server, "Pod", WL_NS, name)
        if variant == "pod_relabel":
            pod["metadata"]["labels"] = {"stolen": "1"}
        elif variant == "pod_reuid":
            pod["metadata"]["uid"] = "0" * 8
        return Probe(server, method, f"/v1/objects/Pod/{WL_NS}/{name}",
                     {"object": pod}, bearer)
    # default / open_server / not_leader / every non-node tier: a benign
    # full-object re-PUT of a pod the fleet seeded on that server
    target = "p-open" if server == "open" else (
        "p-own" if server == "follower" else "p-admin"
    )
    pod = _current(fleet, server, "Pod", WL_NS, target)
    return Probe(server, method, f"/v1/objects/Pod/{WL_NS}/{target}",
                 {"object": pod}, bearer)


def _build_subresource_patch(fleet: Fleet, server: str, tier: str,
                             variant: str, bearer: Optional[str]) -> Probe:
    from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE

    method = "PATCH"
    if variant == "own_node_status":
        return Probe(server, method,
                     f"/v1/objects/Node/{NODE_NAMESPACE}/{NODE_NAME}/status",
                     {"patch": {"status": {"last_heartbeat": 124.0}}}, bearer)
    if variant == "cordon_key":
        return Probe(server, method,
                     f"/v1/objects/Node/{NODE_NAMESPACE}/{NODE_NAME}/status",
                     {"patch": {"status": {"unschedulable": False}}}, bearer)
    if variant == "conditions_key":
        return Probe(server, method,
                     f"/v1/objects/Node/{NODE_NAMESPACE}/{NODE_NAME}/status",
                     {"patch": {"status": {"conditions": []}}}, bearer)
    if variant == "spec_subresource":
        return Probe(server, method, f"/v1/objects/Pod/{WL_NS}/p-own/spec",
                     {"patch": {"spec": {"hostname": "authz-probe"}}}, bearer)
    if variant == "other_pod_status":
        return Probe(server, method,
                     f"/v1/objects/Pod/{WL_NS}/p-other/status",
                     {"patch": {"status": {"message": "authz-probe"}}},
                     bearer)
    if variant == "absent_pod_status":
        return Probe(server, method, f"/v1/objects/Pod/{WL_NS}/p-gone/status",
                     {"patch": {"status": {"message": "authz-probe"}}},
                     bearer)
    if variant == "uid_precondition_overwritten":
        # the client LIES about the uid; the server's pin must overwrite
        # it with the verified incarnation's uid, so this succeeds —
        # with the pin skipped (mutant) the lie survives to the store's
        # uid precondition and bounces Conflict
        return Probe(server, method, f"/v1/objects/Pod/{WL_NS}/p-uid/status",
                     {"patch": {"metadata": {"uid": "not-the-real-uid"},
                                "status": {"message": "authz-probe"}}},
                     bearer)
    # default / own_pod_status / every non-node tier
    target = "p-admin" if tier == "admin" else "p-own"
    return Probe(server, method, f"/v1/objects/Pod/{WL_NS}/{target}/status",
                 {"patch": {"status": {"message": "authz-probe"}}}, bearer)


def _build_batch(fleet: Fleet, server: str, tier: str, variant: str,
                 bearer: Optional[str]) -> Probe:
    from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE

    def node_item() -> Dict[str, Any]:
        return {"kind": "Node", "namespace": NODE_NAMESPACE,
                "name": NODE_NAME, "subresource": "status",
                "patch": {"status": {"last_heartbeat": 125.0}}}

    def pod_item(name: str, subresource: Optional[str] = "status"
                 ) -> Dict[str, Any]:
        item: Dict[str, Any] = {
            "kind": "Pod", "namespace": WL_NS, "name": name,
            "patch": {"status": {"message": "authz-probe"}},
        }
        if subresource is not None:
            item["subresource"] = subresource
        return item

    if variant == "own_status_batch":
        items = [node_item(), pod_item("p-own")]
    elif variant == "item_crosses_tier":
        # first item is squarely in scope; the SECOND crosses onto
        # another node's pod — per-item authz must fail the whole batch
        items = [node_item(), pod_item("p-other")]
    elif variant == "spec_item":
        items = [pod_item("p-own", subresource=None)]
    else:
        items = [pod_item("p-admin")]
    return Probe(server, "POST", "/v1/patch-batch", {"items": items}, bearer)


# ---------------------------------------------------------------------------
# firing + diffing
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Observed:
    status: int
    error: Optional[str]
    message: str


def _fire(fleet: Fleet, probe_req: Probe, timeout: float = 10.0) -> Observed:
    url = fleet.url(probe_req.server) + probe_req.path
    data = (json.dumps(probe_req.body).encode()
            if probe_req.body is not None else None)
    req = urlrequest.Request(url, data=data, method=probe_req.method)
    req.add_header("Content-Type", "application/json")
    if probe_req.bearer is not None:
        req.add_header("Authorization", f"Bearer {probe_req.bearer}")
    try:
        with urlrequest.urlopen(req, timeout=timeout) as resp:
            status, raw = resp.status, resp.read()
    except urlerror.HTTPError as e:
        status, raw = e.code, e.read()
    try:
        payload = json.loads(raw) if raw else {}
    except json.JSONDecodeError:
        payload = {}
    err = payload.get("error") if isinstance(payload, dict) else None
    msg = payload.get("message", "") if isinstance(payload, dict) else ""
    return Observed(status=status, error=err, message=str(msg))


@dataclass(frozen=True)
class Finding:
    token: str
    declared: str
    observed_status: Optional[int]
    observed_error: Optional[str]
    message: str

    def render(self) -> str:
        obs = ("(not fired)" if self.observed_status is None
               else f"{self.observed_status} {self.observed_error or '-'}")
        return (f"AUTHZ DIFF {self.token}\n"
                f"  declared: {self.declared}\n"
                f"  observed: {obs}\n"
                f"  {self.message}")


@dataclass
class ProbeReport:
    backend: str
    cells: int
    findings: List[Finding]
    observed: Dict[str, Tuple[int, Optional[str]]]

    @property
    def ok(self) -> bool:
        return not self.findings

    def render(self) -> str:
        head = (f"authz[{self.backend}]: {self.cells} cell(s) probed, "
                f"{len(self.findings)} diff(s)")
        if self.ok:
            return head + " — clean"
        return "\n".join([head] + [f.render() for f in self.findings])


@dataclass(frozen=True)
class Cell:
    route: str
    tier: str
    variant: str
    expected: Outcome

    @property
    def token(self) -> str:
        return encode_token(self.route, self.tier, self.variant)


def iter_cells(policy: Policy) -> List[Cell]:
    out: List[Cell] = []
    for route, tiers in policy.routes.items():
        for tier in TIERS:
            for variant, outcome in tiers[tier].items():
                out.append(Cell(route, tier, variant, outcome))
    return out


def coverage_findings(
    policy: Policy, servable: Optional[List[str]] = None
) -> List[Finding]:
    """Routes the live router serves but the matrix does not declare —
    the fail-closed direction for NEW endpoints — plus mirror drift
    between the server's peer table and the client fabric's."""
    live = list(servable if servable is not None else servable_routes())
    out: List[Finding] = []
    for route in live:
        if route not in policy.routes:
            out.append(Finding(
                token=encode_token(route, "*", "undeclared"),
                declared="<absent>", observed_status=None,
                observed_error=None,
                message=(f"servable route {route!r} has no entry in "
                         f"authz_policy.json — declare its posture for "
                         f"every tier before it ships"),
            ))
    try:
        from mpi_operator_tpu.machinery.http_store import StoreServer
        from mpi_operator_tpu.machinery.replica_wire import peer_wire_routes

        server_side = sorted(
            "/v1/replica/" + wire for wire in StoreServer._PEER_ROUTE_METHODS
        )
        if server_side != peer_wire_routes():
            out.append(Finding(
                token=encode_token("POST /v1/replica/*", "*", "mirror-drift"),
                declared=str(server_side), observed_status=None,
                observed_error=None,
                message=(f"server peer routes {server_side} != client "
                         f"fabric routes {peer_wire_routes()} — a route "
                         f"added to one table but not the other 404s in "
                         f"a real failover"),
            ))
    except ImportError:
        pass
    return out


# secret-named exposition labels: the label NAME suggests a credential
# and the value is non-empty → a secret is riding the monitoring plane
_SECRET_LABEL_RE = re.compile(
    r'([A-Za-z_]*(?:token|secret|passw|credential|bearer)[A-Za-z_]*)'
    r'="([^"]+)"',
    re.IGNORECASE,
)


def scan_exposition(body: str) -> List[str]:
    """SEC001's runtime twin: no fleet credential and no secret-named
    label value may appear in a metrics exposition body. Returns
    human-readable violations (empty = clean); values are NEVER echoed
    into the messages."""
    out: List[str] = []
    for tier, tok in _FLEET_TOKENS.items():
        if tok is not None and tok in body:
            out.append(f"the {tier}-tier bearer token value appears in "
                       f"the exposition body")
    for m in _SECRET_LABEL_RE.finditer(body):
        out.append(f"secret-named exposition label {m.group(1)!r} carries "
                   f"a non-empty value")
    return out


def _ops_findings(fleet: Fleet, policy: Policy) -> Tuple[int, List[Finding]]:
    cells = 0
    out: List[Finding] = []
    for route, outcome in sorted(policy.ops_server.items()):
        method, path = route.split(" ", 1)
        obs = _fire(fleet, Probe("ops", method, path, None, None))
        cells += 1
        if obs.status != outcome.status:
            out.append(Finding(
                token=encode_token(route, "anon", "ops_server"),
                declared=outcome.raw, observed_status=obs.status,
                observed_error=obs.error,
                message="ops-server posture diverged from the declaration",
            ))
        if path == "/metrics" and obs.status == 200:
            url = fleet.url("ops") + path
            with urlrequest.urlopen(url, timeout=10.0) as resp:
                text = resp.read().decode("utf-8", "replace")
            for violation in scan_exposition(text):
                out.append(Finding(
                    token=encode_token(route, "anon", "secret_scan"),
                    declared="no secret in any exposition body",
                    observed_status=200, observed_error=None,
                    message=violation,
                ))
    return cells, out


# ---------------------------------------------------------------------------
# probe / replay
# ---------------------------------------------------------------------------


def probe(
    backend: str = "memory",
    *,
    policy: Optional[Policy] = None,
    policy_path: Optional[str] = None,
    servable: Optional[List[str]] = None,
    mutant: Optional[str] = None,
    denied_only: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> ProbeReport:
    """Boot a fresh fleet and fire every matrix cell, diffing observed
    (status, typed error) against the declaration. ``denied_only``
    restricts to deny/pass cells — the reduced tier-1 set (no
    state-mutating allow cells, so it is also the set the cross-backend
    parity suite compares verbatim). ``mutant`` arms a seeded bug
    first; see MUTANTS."""
    policy = policy or load_policy(policy_path, servable=servable)
    findings = coverage_findings(policy, servable)
    observed: Dict[str, Tuple[int, Optional[str]]] = {}
    fleet = make_fleet(backend)
    cells = 0
    try:
        if mutant is not None:
            if mutant not in MUTANTS:
                raise AuthzConfigError(
                    f"unknown mutant {mutant!r} (have {sorted(MUTANTS)})"
                )
            MUTANTS[mutant].apply(fleet)
        for cell in iter_cells(policy):
            if denied_only and cell.expected.kind == "allow":
                continue
            obs = _fire(fleet, build_probe(fleet, cell.route, cell.tier,
                                           cell.variant))
            cells += 1
            observed[cell.token] = (obs.status, obs.error)
            if not cell.expected.matches(obs.status, obs.error):
                findings.append(Finding(
                    token=cell.token, declared=cell.expected.raw,
                    observed_status=obs.status, observed_error=obs.error,
                    message=obs.message,
                ))
        if not denied_only:
            ops_cells, ops_diffs = _ops_findings(fleet, policy)
            cells += ops_cells
            findings.extend(ops_diffs)
    finally:
        fleet.close()
    if log:
        log(f"authz[{backend}]: {cells} cell(s), "
            f"{len(findings)} diff(s)")
    return ProbeReport(backend=backend, cells=cells, findings=findings,
                       observed=observed)


def replay(
    token: str,
    backend: str = "memory",
    *,
    mutant: Optional[str] = None,
    policy_path: Optional[str] = None,
) -> Optional[Finding]:
    """Re-probe EXACTLY one cell on a fresh fleet. Returns the Finding
    when the cell still diffs, None when it probes clean."""
    route, tier, variant = parse_token(token)
    policy = load_policy(policy_path)
    for cell in iter_cells(policy):
        if (cell.route, cell.tier, cell.variant) == (route, tier, variant):
            break
    else:
        raise AuthzConfigError(
            f"token {token!r} names no declared matrix cell"
        )
    fleet = make_fleet(backend)
    try:
        if mutant is not None:
            if mutant not in MUTANTS:
                raise AuthzConfigError(
                    f"unknown mutant {mutant!r} (have {sorted(MUTANTS)})"
                )
            MUTANTS[mutant].apply(fleet)
        obs = _fire(fleet, build_probe(fleet, route, tier, variant))
    finally:
        fleet.close()
    if cell.expected.matches(obs.status, obs.error):
        return None
    return Finding(token=token, declared=cell.expected.raw,
                   observed_status=obs.status, observed_error=obs.error,
                   message=obs.message)


# ---------------------------------------------------------------------------
# seeded mutants: the bug classes four review passes kept finding by hand
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Mutant:
    name: str
    description: str
    token: str  # the cell whose diff must catch it
    apply: Callable[[Fleet], None]


def _mut_node_spec_patch(fleet: Fleet) -> None:
    """Mutant 1: the node tier's patch gate rewrites every subresource to
    'status' — i.e. the status-only restriction is gone and a node can
    drive spec patches (the PR 12 bug class)."""
    srv = fleet.main
    orig = srv._agent_patch_denied

    def mutated(rest: List[str], patch: Any, node: str):
        if len(rest) == 4:
            return orig([rest[0], rest[1], rest[2], "status"], patch, node)
        return orig(rest, patch, node)

    srv._agent_patch_denied = mutated


def _mut_peer_behind_open(fleet: Fleet) -> None:
    """Mutant 2: the OPEN server's auth gate runs the unauthenticated
    early-out BEFORE the peer-route fence — replication RPCs become
    reachable on any open store (the PR 13 ordering bug)."""
    srv = fleet.open
    handler_cls = srv._httpd.RequestHandlerClass
    orig = handler_cls._auth_error

    def mutated(self, method: str, body):
        if srv.token is None and not srv.agent_tokens:
            self._tier = None
            return None
        return orig(self, method, body)

    handler_cls._auth_error = mutated


def _mut_read_mutates(fleet: Fleet) -> None:
    """Mutant 3: the read tier's mutation denial is dropped — the
    'read-only' token silently becomes a second admin credential."""
    handler_cls = fleet.main._httpd.RequestHandlerClass
    orig = handler_cls._auth_error

    def mutated(self, method: str, body):
        denied = orig(self, method, body)
        if denied is not None and denied[0] == 403 and "read-only" in denied[1]:
            return None
        return denied

    handler_cls._auth_error = mutated


def _mut_cordon_dropped(fleet: Fleet) -> None:
    """Mutant 4: the cordon-key denial is dropped from the node patch
    gate — a compromised node can un-cordon itself (the PR 10 bug)."""
    srv = fleet.main
    orig = srv._agent_patch_denied

    def mutated(rest: List[str], patch: Any, node: str):
        denied = orig(rest, patch, node)
        if denied is not None and "unschedulable" in denied[1]:
            return None
        return denied

    srv._agent_patch_denied = mutated


def _mut_uid_pin_skipped(fleet: Fleet) -> None:
    """Mutant 5: the uid pin is a no-op — the client-supplied uid
    precondition survives to the store, so the authz-to-apply window is
    back (the PR 2 TOCTOU) and the probe's deliberate uid lie bounces."""
    fleet.main._pin_uid = lambda patch, uid: None


def _mut_batch_collapsed(fleet: Fleet) -> None:
    """Mutant 6: per-item batch authz collapses to batch level — only
    the FIRST item is checked, so an in-scope heartbeat smuggles an
    out-of-scope pod write in the same batch."""
    srv = fleet.main
    orig = srv._agent_denied

    def mutated(method: str, path: str, body: Any, node: str):
        if (method == "POST" and isinstance(body, dict)
                and isinstance(body.get("items"), list)):
            body = dict(body, items=body["items"][:1])
        return orig(method, path, body, node)

    srv._agent_denied = mutated


MUTANTS: Dict[str, Mutant] = {
    m.name: m for m in (
        Mutant(
            name="node-spec-patch-allowed",
            description="node tier allowed a spec patch (status-only "
                        "restriction dropped)",
            token=encode_token(
                "PATCH /v1/objects/{kind}/{ns}/{name}/{subresource}",
                "node", "spec_subresource"),
            apply=_mut_node_spec_patch,
        ),
        Mutant(
            name="peer-routes-behind-open-early-out",
            description="peer replication routes moved behind the "
                        "open-server early-out",
            token=encode_token("POST /v1/replica/append-entries",
                               "anon", "open_server"),
            apply=_mut_peer_behind_open,
        ),
        Mutant(
            name="read-token-accepts-mutation",
            description="read tier's mutation denial dropped",
            token=encode_token("POST /v1/objects", "read", "default"),
            apply=_mut_read_mutates,
        ),
        Mutant(
            name="cordon-key-denial-dropped",
            description="node tier may touch status.unschedulable",
            token=encode_token(
                "PATCH /v1/objects/{kind}/{ns}/{name}/{subresource}",
                "node", "cordon_key"),
            apply=_mut_cordon_dropped,
        ),
        Mutant(
            name="uid-pin-precondition-skipped",
            description="the server-side uid pin no longer overwrites "
                        "the client's uid claim",
            token=encode_token(
                "PATCH /v1/objects/{kind}/{ns}/{name}/{subresource}",
                "node", "uid_precondition_overwritten"),
            apply=_mut_uid_pin_skipped,
        ),
        Mutant(
            name="batch-item-authz-collapsed",
            description="patch-batch authz checks only the first item",
            token=encode_token("POST /v1/patch-batch",
                               "node", "item_crosses_tier"),
            apply=_mut_batch_collapsed,
        ),
    )
}


# ---------------------------------------------------------------------------
# self test: the detector-of-the-detector bar
# ---------------------------------------------------------------------------


def self_test(log: Optional[Callable[[str], None]] = None) -> List[str]:
    """(1) the full real matrix probes clean on memory AND sqlite
    fleets; (2) every denied/pass cell observes IDENTICAL (status,
    error) across the two backends; (3) each seeded mutant is caught on
    its expected token, and replaying that token twice on fresh mutant
    fleets is twice-identical; (4) an injected undeclared route fails
    closed as a coverage finding; (5) the /metrics wire capture carries
    no secret. Returns failure strings (empty = pass)."""
    failures: List[str] = []
    observed_by_backend: Dict[str, Dict[str, Tuple[int, Optional[str]]]] = {}
    for backend in ("memory", "sqlite"):
        report = probe(backend=backend, log=log)
        observed_by_backend[backend] = report.observed
        for f in report.findings:
            failures.append(
                f"{backend}: real server diffs from the declared matrix: "
                f"{f.token} declared={f.declared} "
                f"observed={f.observed_status}:{f.observed_error}"
            )
    mem, sql = observed_by_backend["memory"], observed_by_backend["sqlite"]
    for token in sorted(set(mem) & set(sql)):
        if mem[token] != sql[token]:
            failures.append(
                f"cross-backend parity: {token} observed {mem[token]} on "
                f"memory but {sql[token]} on sqlite"
            )
    for name in sorted(MUTANTS):
        m = MUTANTS[name]
        report = probe(backend="memory", mutant=name)
        tokens = {f.token for f in report.findings}
        if m.token not in tokens:
            failures.append(
                f"mutant {name}: expected finding {m.token} not among "
                f"{sorted(tokens)}"
            )
            continue
        first = replay(m.token, mutant=name)
        second = replay(m.token, mutant=name)
        if first is None or second is None:
            failures.append(
                f"mutant {name}: --replay {m.token} did not reproduce "
                f"the diff"
            )
        elif ((first.observed_status, first.observed_error)
              != (second.observed_status, second.observed_error)):
            failures.append(
                f"mutant {name}: replay is nondeterministic "
                f"({first.observed_status}:{first.observed_error} vs "
                f"{second.observed_status}:{second.observed_error})"
            )
        if log:
            log(f"authz: mutant {name} caught on {m.token}")
    injected = "POST /v1/authz-selftest-injected"
    inj_policy = load_policy(servable=servable_routes() + [injected])
    inj = coverage_findings(inj_policy, servable_routes() + [injected])
    if not any(injected in f.token for f in inj):
        failures.append(
            "undeclared-route injection: a servable route absent from the "
            "matrix did NOT produce a coverage finding"
        )
    return failures
