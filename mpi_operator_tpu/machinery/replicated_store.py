"""Replicated HA store: a 3-node log-shipping replica set over SqliteStore.

The store was the control plane's last single point of failure (ROADMAP
item 1): PR 3 proved single-node crash-recovery, nothing more. This module
is the kube-apiserver/etcd split's missing half — a leased leader accepts
all mutations and synchronously ships the committed-op WAL (every
``SqliteStore._txn`` commit is already a log row carrying the object at
its rv) to followers, **acking a write only after a majority has durably
applied it**. Followers serve reads and watch fan-out from their own
sqlite files (listers/informers may lag, never regress rv); a new leader
is elected by quorum lease takeover with log-tail reconciliation.

Protocol, in five rules:

1. **Epochs are votes.** A node's durable ``epoch`` (replica_meta, via the
   same ``_txn`` seam every write rides) only ever increases, and adopting
   an epoch IS granting that epoch's single vote. Majorities intersect, so
   **at most one leader exists per epoch** — the chaos e2e asserts exactly
   that from the leadership log.
2. **Leases fence.** A follower refuses votes while its current leader's
   lease (refreshed by every append/heartbeat) is still running, so a
   live leader cannot be deposed by a flaky candidate; a leader that
   cannot renew against a majority steps down at its own (shorter) local
   deadline before any grantor's lease can expire.
3. **Commit = majority-durable.** The leader commits locally (its sqlite
   IS one of the copies), ships the new log rows to every reachable
   follower, and acks the client only when ``majority`` copies (itself
   included) have applied. Shipping to ALL reachable followers before
   returning is what makes follower reads read-your-writes on a healthy
   set — the property the differential fuzzer leans on.
4. **Election reconciles tails.** A winning candidate adopts the highest
   applied rv among its granting quorum (pulling the missing tail, or a
   full snapshot when the tail was trimmed). Any ACKED write is on a
   majority; any quorum intersects that majority; therefore the new
   leader's history contains every acked write — the no-acked-write-lost
   invariant.
5. **Divergent suffixes truncate.** Entries are shipped with the previous
   entry's content hash; a follower whose same-rv history hashes
   differently (it holds a dead epoch's unacked suffix — e.g. the old
   leader's local commit that never reached a majority) resyncs from a
   leader snapshot, wiping the suffix. A write the leader definitively
   rejected is therefore never resurrected; a write that died
   *indeterminately* (:class:`ReplicationUnavailable` — the leader lost
   its majority mid-ship) may surface or vanish, exactly like an
   apiserver timeout, and is documented as such.

Deployment shape: each node's duck-typed surface can sit behind its own
``StoreServer``; follower mutations raise :class:`NotLeader` (421 on the
wire, with a leader hint) and ``HttpStoreClient`` rotates/redirects.
In-process, :class:`ReplicaClient` is the same failover client without
the sockets — it is what the analysis gates (storecheck / linearize /
crashpoints) drive, the replica set being just another duck-typed
backend to them.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.sqlite_store import (
    LogTruncated,
    SqliteStore,
    entry_hash,
)
from mpi_operator_tpu.machinery.store import (
    NotLeader,
    ReplicationUnavailable,
)
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.replica")

LEADER = "leader"
FOLLOWER = "follower"


class PeerUnreachable(ConnectionError):
    """The transport could not deliver (node down / link partitioned)."""


class StaleEpoch(RuntimeError):
    """An RPC arrived from a dead epoch: the sender has been superseded
    and must step down (the fencing signal)."""

    def __init__(self, current_epoch: int):
        super().__init__(f"superseded by epoch {current_epoch}")
        self.current_epoch = current_epoch


class PeerHub:
    """In-process replica transport with fault injection: per-node down
    flags (SIGKILL semantics) and symmetric pairwise partitions — the
    fabric seam ChaosScript ``partition`` actions drive. Calls are
    synchronous method dispatch; an unreachable destination raises
    :class:`PeerUnreachable` exactly where a socket would ECONNREFUSED."""

    def __init__(self):
        self._lock = threading.Lock()
        self._nodes: Dict[str, "ReplicaNode"] = {}
        self._down: Dict[str, bool] = {}
        self._cuts: set = set()  # frozenset({a, b}) pairs

    def register(self, node: "ReplicaNode") -> None:
        with self._lock:
            self._nodes[node.node_id] = node
            self._down[node.node_id] = False

    def set_down(self, node_id: str, down: bool) -> None:
        with self._lock:
            self._down[node_id] = down

    # -- the chaos fabric surface (ChaosController(fabric=hub)) -------------

    def partition(self, a: str, b: str) -> None:
        """Blackhole BOTH directions between two named endpoints."""
        with self._lock:
            if a not in self._nodes or b not in self._nodes:
                raise KeyError(f"unknown partition endpoint in ({a!r}, {b!r})")
            self._cuts.add(frozenset((a, b)))

    def heal(self, a: str, b: str) -> None:
        with self._lock:
            self._cuts.discard(frozenset((a, b)))

    def heal_all(self) -> None:
        with self._lock:
            self._cuts.clear()

    def call(self, src: str, dst: str, method: str, *args) -> Any:
        with self._lock:
            if self._down.get(dst, True) or self._down.get(src, False):
                raise PeerUnreachable(f"{dst} is down")
            if frozenset((src, dst)) in self._cuts:
                raise PeerUnreachable(f"{src}<->{dst} partitioned")
            node = self._nodes[dst]
        # dispatch OUTSIDE the hub lock: a handler may itself call peers
        return getattr(node, method)(*args)


def _monotonic() -> float:
    return time.monotonic()


class ReplicaNode:
    """One replica-set member: a SqliteStore plus the replication role.

    Duck-typed store surface: reads/watches serve locally on any role;
    mutations require the lease and run commit-then-ship-then-ack under
    one gate so ship order equals rv order. RPC handler methods
    (request_vote / append_entries / fetch_entries / install_snapshot /
    replica_status) are invoked by peers through the hub.
    """

    def __init__(self, node_id: str, path: str, hub: PeerHub, rset:
                 "ReplicaSet", *, lease_duration: float,
                 poll_interval: float = 0.05):
        self.node_id = node_id
        self.path = path
        self.hub = hub
        self.rset = rset
        self.lease_duration = lease_duration
        self.poll_interval = poll_interval
        self.backing = SqliteStore(path, poll_interval=poll_interval)
        # durable election state: adopting an epoch IS this node's one
        # vote in it (rule 1); survives crash/restart via replica_meta
        self.epoch = int(self.backing.get_meta("epoch", "0"))
        self._state_lock = threading.RLock()
        self.role = FOLLOWER
        self.leader_id: Optional[str] = None
        self.crashed = False
        # follower: how long the current leader's lease runs on MY clock;
        # leader: my own (stricter) renew deadline
        self._lease_until = 0.0
        self._lease_deadline = 0.0
        # leader ship cursor + per-peer applied rv (the lag metric)
        self._ship_lock = threading.Lock()
        self._shipped_rv = self.backing.current_rv()
        self._peer_rv: Dict[str, int] = {}
        # serializes the WHOLE fence-check→apply window of incoming
        # append_entries/install_snapshot: without it a stale leader's
        # delayed append could pass the epoch fence, stall, and then
        # interleave its dead-epoch rows into a newer leader's apply
        # (duplicate-rv IntegrityError or a gapped follower history)
        self._apply_lock = threading.Lock()

    # -- small helpers -------------------------------------------------------

    @property
    def peers(self) -> List[str]:
        return [n for n in self.rset.node_ids if n != self.node_id]

    @property
    def majority(self) -> int:
        return len(self.rset.node_ids) // 2 + 1

    def _leader_hint(self) -> Optional[str]:
        with self._state_lock:
            lid = self.node_id if self.role == LEADER else self.leader_id
        return self.rset.advertise.get(lid, lid) if lid else None

    def _adopt_epoch(self, epoch: int) -> None:
        """Durably advance to ``epoch`` (caller holds _state_lock)."""
        self.backing.set_meta("epoch", str(epoch))
        self.epoch = epoch

    def _step_down(self, why: str) -> None:
        with self._state_lock:
            if self.role == LEADER:
                log.info("%s: stepping down (%s)", self.node_id, why)
            self.role = FOLLOWER
            # hold off on campaigning for a full lease: peers granted the
            # superseding/surviving side time to establish itself
            self._lease_until = _monotonic() + self.lease_duration

    def _require_leader(self) -> int:
        """Validate leadership and return THE REIGN'S EPOCH, atomically:
        everything shipped on behalf of this check must be stamped with
        exactly this epoch — re-reading self.epoch at ship time would
        let a leader deposed mid-write ship its entry as the NEW epoch's
        traffic, sailing past the StaleEpoch fence."""
        with self._state_lock:
            if self.crashed:
                raise PeerUnreachable(f"{self.node_id} is down")
            if self.role != LEADER:
                raise NotLeader(
                    f"replica {self.node_id} is a follower; mutations go "
                    f"to the leased leader",
                    leader=self._leader_hint(),
                )
            if _monotonic() > self._lease_deadline:
                raise NotLeader(
                    f"replica {self.node_id}'s lease expired; awaiting "
                    f"re-election",
                    leader=None,
                )
            return self.epoch

    # -- replication (leader side) ------------------------------------------

    def _leader_write(self, fn: Callable[[], Any]) -> Any:
        """Commit locally, ship the new log rows, ack on majority. One
        gate serializes writers so the ship stream is exactly the commit
        stream; store errors (Conflict/NotFound/...) raise before any
        commit and ship nothing — they stay DEFINITE failures."""
        with self._ship_lock:
            epoch = self._require_leader()
            result = fn()
            # the ship span covers commit-to-majority-ack (the HA write
            # tax); its duration lands in the ship-latency histogram at
            # close. Nested under whatever span the writer holds (e.g.
            # the store server's request span), so `ctl trace` shows the
            # replication hop inside the write that paid for it.
            t0 = time.perf_counter()
            with trace.start_span(
                "replica.ship",
                attrs={"node": self.node_id, "epoch": epoch},
            ):
                self._replicate(epoch)
            metrics.replication_ship_latency.observe(
                time.perf_counter() - t0
            )
            return result

    def _replicate(self, epoch: int) -> None:
        tail = self.backing.log_tail(self._shipped_rv)
        if not tail:
            # an empty tail after fn() is normally just an all-failure
            # patch_batch (nothing committed). But if the REIGN advanced
            # mid-write, a new leader's resync may have truncated the
            # just-committed entry out of our local history before we
            # could ship it — returning success would silently ack a
            # write that exists nowhere. History rewrites always ride an
            # epoch advance, so the reign check is the exact detector.
            with self._state_lock:
                if self.epoch != epoch:
                    raise ReplicationUnavailable(
                        f"superseded by epoch {self.epoch} mid-write: "
                        f"the local commit may have been truncated by "
                        f"the new leader's history — outcome "
                        f"INDETERMINATE, re-read before retrying"
                    )
            return
        acks = 1  # the local sqlite commit is copy #1
        for peer in self.peers:
            if self._ship_to(peer, epoch, self._shipped_rv, tail):
                acks += 1
        self._shipped_rv = tail[-1]["rv"]
        self._update_lag()
        if acks >= self.majority:
            with self._state_lock:
                # a majority-acked ship doubles as a lease renewal — but
                # only for the reign that shipped it: a leader deposed
                # mid-write must not resurrect its deadline
                if self.role == LEADER and self.epoch == epoch:
                    self._lease_deadline = max(
                        self._lease_deadline,
                        _monotonic() + self.lease_duration,
                    )
            return
        self._step_down("write could not reach a majority")
        raise ReplicationUnavailable(
            f"write committed on {acks}/{len(self.rset.node_ids)} replicas "
            f"(majority {self.majority} unreachable): outcome INDETERMINATE "
            f"— re-read before retrying"
        )

    def _ship_to(self, peer: str, epoch: int, prev_rv: int,
                 entries: List[Dict[str, Any]]) -> bool:
        """Push a tail to one follower, walking it through lag catch-up
        (``behind``) and divergent-suffix truncation (``divergent`` →
        snapshot install). Returns True when the follower's applied rv
        reaches the tail's end."""
        target_rv = entries[-1]["rv"] if entries else prev_rv
        try:
            for _ in range(4):  # behind/divergent round-trips, bounded
                res = self.hub.call(
                    self.node_id, peer, "append_entries",
                    epoch, self.node_id, prev_rv,
                    self.backing.tail_hash(prev_rv), entries,
                )
                applied = res.get("applied")
                if applied is not None and applied >= target_rv:
                    self._peer_rv[peer] = applied
                    return True
                if "behind" in res:
                    prev_rv = res["behind"]
                elif res.get("divergent"):
                    snap = self.backing.snapshot_state()
                    res2 = self.hub.call(
                        self.node_id, peer, "install_snapshot",
                        epoch, self.node_id, snap,
                    )
                    self._peer_rv[peer] = prev_rv = res2["applied"]
                    if prev_rv >= target_rv:
                        return True
                else:
                    return False
                try:
                    entries = self.backing.log_tail(prev_rv)
                except LogTruncated:
                    prev_rv = -1  # force the snapshot path next loop
                    entries = []
                    continue
            return False
        except PeerUnreachable:
            return False
        except StaleEpoch as e:
            self._step_down(f"fenced by epoch {e.current_epoch}")
            raise ReplicationUnavailable(
                f"superseded by epoch {e.current_epoch} mid-ship: outcome "
                f"INDETERMINATE — re-read before retrying"
            ) from None

    def _update_lag(self) -> None:
        head = self.backing.current_rv()
        for peer, rv in self._peer_rv.items():
            metrics.store_replication_lag.set(
                max(0, head - rv), follower=peer,
            )

    def _heartbeat(self, epoch: int) -> int:
        """Empty append to every peer: refreshes their leases, drags
        laggards up to the ship cursor. Returns reachable copies (self
        included). MUST run under _ship_lock: racing a concurrent
        _replicate on the shared ship cursor would read it mid-advance
        and misdiagnose a healthy follower as divergent (a spurious
        snapshot resync) or double-apply the in-flight rows. ``epoch``
        is the reign being renewed, captured atomically with the role
        check — never re-read at ship time."""
        acks = 1
        for peer in self.peers:
            try:
                if self._ship_to(peer, epoch, self._shipped_rv, []):
                    acks += 1
            except ReplicationUnavailable:
                return acks  # fenced mid-heartbeat: already stepped down
        self._update_lag()
        return acks

    def renew(self) -> None:
        """Leader tick: heartbeat; renew the local deadline on majority,
        step down once it passes without one."""
        with self._state_lock:
            if self.role != LEADER or self.crashed:
                return
            epoch = self.epoch
        with self._ship_lock:
            acks = self._heartbeat(epoch)
        now = _monotonic()
        with self._state_lock:
            if self.role != LEADER or self.epoch != epoch:
                return
            if acks >= self.majority:
                self._lease_deadline = max(
                    self._lease_deadline, now + self.lease_duration
                )
            elif now > self._lease_deadline:
                self._step_down("lease renewal lost its majority")

    # -- election ------------------------------------------------------------

    def campaign(self) -> bool:
        """Traced wrapper over :meth:`_campaign`: a WON election's
        campaign-start-to-leadership time is the failover duration PERF
        round 8 clocked by hand — now a histogram + a ``replica.election``
        span (`ctl trace --last-incident` anchors on it)."""
        t0 = _monotonic()
        with trace.start_span(
            "replica.election", attrs={"node": self.node_id}
        ) as sp:
            won = self._campaign()
            sp.set_attr("won", won)
            if won:
                sp.set_attr("epoch", self.epoch)
        if won:
            metrics.failover_duration.observe(_monotonic() - t0)
        return won

    def _campaign(self) -> bool:
        """Try to take the lease: adopt epoch+1 (the self-vote), gather
        grants, reconcile the log tail to the quorum max (rule 4), then
        lead. A refusal carries the refuser's epoch; a candidate whose
        epoch lagged the quorum (a healed ex-minority node) adopts the
        learned epoch and retries once ABOVE it — without this, a node
        that slept through elections needs two external campaign calls
        to even be eligible. Returns True on a won election."""
        votes = 0
        tails: Dict[str, int] = {}
        for _attempt in (0, 1, 2):
            with self._state_lock:
                if self.crashed:
                    return False
                if self.role == LEADER:
                    return True
                target = self.epoch + 1
            # PRE-VOTE (Raft §9.6): ask whether a majority WOULD grant
            # before durably adopting the new epoch. Without it, a healed
            # minority node's doomed campaign leaves a higher epoch
            # behind, and the live leader's next ship to it gets
            # StaleEpoch-fenced — an indeterminate write + a spurious
            # failover on every partition heal, the exact disruption
            # rule 2 promises cannot happen.
            would, behind_by = 1, 0
            for peer in self.peers:
                try:
                    res = self.hub.call(self.node_id, peer, "request_vote",
                                        target, self.node_id, True)
                except PeerUnreachable:
                    continue
                if res.get("granted"):
                    would += 1
                else:
                    behind_by = max(behind_by, res.get("epoch", 0))
            if would < self.majority:
                if behind_by < target:
                    return False  # refused on leases: genuinely doomed
                with self._state_lock:
                    if behind_by > self.epoch:
                        # our epoch lagged the quorum (a healed minority
                        # node): LEARN it — adopting an epoch that
                        # already exists elsewhere fences nobody — and
                        # retry above it
                        self._adopt_epoch(behind_by)
                continue
            with self._state_lock:
                if self.crashed or self.role == LEADER:
                    return self.role == LEADER
                target = self.epoch + 1
                self._adopt_epoch(target)  # the durable self-vote
                self.leader_id = None
            votes, tails, behind_by = 1, {}, 0
            for peer in self.peers:
                try:
                    res = self.hub.call(self.node_id, peer, "request_vote",
                                        target, self.node_id)
                except PeerUnreachable:
                    continue
                if res.get("granted"):
                    votes += 1
                    tails[peer] = res["rv"]
                else:
                    behind_by = max(behind_by, res.get("epoch", 0))
            if votes >= self.majority:
                break
            if behind_by < target:
                return False  # refused on leases, not on a stale epoch
            with self._state_lock:
                if behind_by > self.epoch:
                    self._adopt_epoch(behind_by)  # learn, retry above it
        if votes < self.majority:
            return False
        my_rv = self.backing.current_rv()
        best = max(tails, key=tails.get, default=None)
        if best is not None and (tails[best] > 0 or my_rv > 0):
            # reconcile against the quorum max at the COMMON history
            # point — behind, EQUAL, or even when this candidate is
            # numerically AHEAD: rv comparison alone cannot distinguish
            # the grantor's acked history from a same-or-higher-numbered
            # dead-epoch suffix (an ex-leader's unacked local commits —
            # a partitioned patch_batch leaves SEVERAL). The catch-up
            # carries our hash at min(rv)s, so the grantor answers with
            # entries (in sync / we're behind), or a snapshot that
            # TRUNCATES our divergent suffix before we lead. Entries
            # above the quorum max are provably unacked (an acked write
            # is on a majority, which every quorum intersects), so
            # truncating them is always legal; skipping the check would
            # let the rejoining ex-leader win and then snapshot ACKED
            # writes OFF the quorum — the exact inversion of rule 4.
            self._catch_up_from(best, min(my_rv, tails[best]))
        with self._ship_lock:
            # reset the ship cursor BEFORE taking leadership: a client
            # write slipping in between the role flip and a later reset
            # would ship from a stale cursor
            self._shipped_rv = self.backing.current_rv()
            self._peer_rv = {}
        with self._state_lock:
            if self.epoch != target:
                return False  # a higher epoch appeared mid-election
            self.role = LEADER
            self.leader_id = self.node_id
            self._lease_deadline = _monotonic() + self.lease_duration
        metrics.store_replication_failovers.inc()
        self.rset._record_leader(target, self.node_id)
        log.info("%s: leading epoch %d at rv %d", self.node_id, target,
                 self._shipped_rv)
        with self._ship_lock:
            # establish leases + drag laggards up NOW, as the new reign
            self._heartbeat(target)
        return True

    def _catch_up_from(self, peer: str, after_rv: int) -> None:
        res = self.hub.call(
            self.node_id, peer, "fetch_entries",
            after_rv, self.backing.tail_hash(after_rv),
        )
        if "snapshot" in res:
            self.backing.load_snapshot(res["snapshot"])
        else:
            self.backing.apply_replicated(res["entries"])

    # -- RPC handlers (invoked through the hub) ------------------------------

    def request_vote(self, epoch: int, candidate_id: str,
                     prevote: bool = False) -> Dict[str, Any]:
        """``prevote=True`` answers "WOULD you grant?" with zero durable
        or volatile state change — the Raft pre-vote probe that keeps a
        doomed campaign from leaving a leader-fencing epoch behind."""
        with self._state_lock:
            if self.crashed:
                raise PeerUnreachable(f"{self.node_id} is down")
            rv = self.backing.current_rv()
            if epoch <= self.epoch:
                return {"granted": False, "rv": rv, "epoch": self.epoch}
            now = _monotonic()
            if self.role == LEADER and now < self._lease_deadline:
                # a live leader does not vote itself out under a flaky
                # candidate (rule 2)
                return {"granted": False, "rv": rv, "epoch": self.epoch}
            if (
                self.role == FOLLOWER
                and self.leader_id is not None
                and self.leader_id != candidate_id
                and now < self._lease_until
            ):
                return {"granted": False, "rv": rv, "epoch": self.epoch}
            if prevote:
                return {"granted": True, "rv": rv, "epoch": self.epoch}
            self._adopt_epoch(epoch)  # THE vote: durable, one per epoch
            self.role = FOLLOWER
            self.leader_id = None
            return {"granted": True, "rv": rv, "epoch": epoch}

    def append_entries(self, epoch: int, leader_id: str, prev_rv: int,
                       prev_hash: Optional[str],
                       entries: List[Dict[str, Any]]) -> Dict[str, Any]:
        with self._apply_lock:
            return self._append_entries_locked(epoch, leader_id, prev_rv,
                                               prev_hash, entries)

    def _append_entries_locked(self, epoch: int, leader_id: str,
                               prev_rv: int, prev_hash: Optional[str],
                               entries: List[Dict[str, Any]]
                               ) -> Dict[str, Any]:
        with self._state_lock:
            if self.crashed:
                raise PeerUnreachable(f"{self.node_id} is down")
            if epoch < self.epoch:
                raise StaleEpoch(self.epoch)
            if epoch > self.epoch:
                self._adopt_epoch(epoch)
            if self.role == LEADER and leader_id != self.node_id:
                # same-epoch second leader is impossible (votes are
                # durable + majorities intersect); this branch is a
                # higher-epoch leader superseding us
                self.role = FOLLOWER
            self.leader_id = leader_id
            self._lease_until = _monotonic() + self.lease_duration
        my_rv = self.backing.current_rv()
        if my_rv < prev_rv:
            return {"behind": my_rv}
        if my_rv > prev_rv:
            if entries and my_rv >= entries[-1]["rv"] and (
                self.backing.tail_hash(entries[-1]["rv"])
                == entry_hash(entries[-1])
            ):
                return {"applied": my_rv}  # duplicate ship: already have it
            return {"divergent": True}
        if prev_rv > 0 and prev_hash is not None:
            mine = self.backing.tail_hash(prev_rv)
            if mine is not None and mine != prev_hash:
                return {"divergent": True}  # dead-epoch suffix at my tail
        if entries:
            self.backing.apply_replicated(entries)
        return {"applied": self.backing.current_rv()}

    def fetch_entries(self, after_rv: int,
                      after_hash: Optional[str]) -> Dict[str, Any]:
        with self._state_lock:
            if self.crashed:
                raise PeerUnreachable(f"{self.node_id} is down")
        if after_rv > 0 and after_hash is not None:
            mine = self.backing.tail_hash(after_rv)
            if mine is not None and mine != after_hash:
                return {"snapshot": self.backing.snapshot_state()}
        try:
            return {"entries": self.backing.log_tail(after_rv)}
        except LogTruncated:
            return {"snapshot": self.backing.snapshot_state()}

    def install_snapshot(self, epoch: int, leader_id: str,
                         snap: Dict[str, Any]) -> Dict[str, Any]:
        with self._apply_lock:
            with self._state_lock:
                if self.crashed:
                    raise PeerUnreachable(f"{self.node_id} is down")
                if epoch < self.epoch:
                    raise StaleEpoch(self.epoch)
                if epoch > self.epoch:
                    self._adopt_epoch(epoch)
                self.role = FOLLOWER
                self.leader_id = leader_id
                self._lease_until = _monotonic() + self.lease_duration
            return {"applied": self.backing.load_snapshot(snap)}

    def replica_status(self) -> Dict[str, Any]:
        """The `ctl store status` / /v1/replica/status payload."""
        with self._state_lock:
            now = _monotonic()
            lease = (self._lease_deadline if self.role == LEADER
                     else self._lease_until) - now
            out = {
                "node": self.node_id,
                "role": self.role if not self.crashed else "down",
                "epoch": self.epoch,
                "applied_rv": (0 if self.crashed
                               else self.backing.current_rv()),
                "lease_remaining_s": round(max(0.0, lease), 3),
                "leader": self._leader_hint(),
            }
            if self.role == LEADER and not self.crashed:
                head = self.backing.current_rv()
                out["lag_entries"] = {
                    p: max(0, head - rv) for p, rv in self._peer_rv.items()
                }
        return out

    # -- duck-typed store surface --------------------------------------------

    def create(self, obj: Any) -> Any:
        return self._leader_write(lambda: self.backing.create(obj))

    def update(self, obj: Any, force: bool = False) -> Any:
        return self._leader_write(lambda: self.backing.update(obj, force))

    def patch(self, kind: str, namespace: str, name: str, patch: Any, *,
              subresource: Optional[str] = None) -> Any:
        return self._leader_write(
            lambda: self.backing.patch(kind, namespace, name, patch,
                                       subresource=subresource)
        )

    def patch_batch(self, items: List[Dict[str, Any]]) -> List[Any]:
        """Per-item semantics come from the backing loop; the whole
        batch's new log rows ship as one tail (per-item errors commit
        nothing and ship nothing, exactly like the single verbs)."""
        return self._leader_write(lambda: self.backing.patch_batch(items))

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        return self._leader_write(
            lambda: self.backing.delete(kind, namespace, name)
        )

    def try_delete(self, kind: str, namespace: str, name: str
                   ) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except KeyError:  # NotFound subclasses KeyError
            return None

    def get(self, kind: str, namespace: str, name: str) -> Any:
        return self.backing.get(kind, namespace, name)

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        return self.backing.try_get(kind, namespace, name)

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[Any]:
        return self.backing.list(kind, namespace, selector)

    def current_rv(self) -> int:
        return self.backing.current_rv()

    def watch(self, kind: Optional[str] = None):
        return self.backing.watch(kind)

    def stop_watch(self, q) -> None:
        self.backing.stop_watch(q)

    def add_relist_listener(self, cb) -> None:
        self.backing.add_relist_listener(cb)

    # -- lifecycle -----------------------------------------------------------

    def crash(self) -> None:
        """SIGKILL semantics: drop the node without any clean shutdown —
        the WAL is left unCheckpointed on disk, exactly what a killed
        process strands. The sqlite connection is ABANDONED, deliberately
        not closed: sqlite3.Connection.close() racing another thread's
        in-flight execute is a C-level crash (a real segfault, observed
        under the auto-renew ticker), and a real SIGKILL runs no close()
        either. A verb already past the crash check simply finishes its
        local commit and then fails the majority ship (the hub is down
        for this node) — the honest INDETERMINATE outcome. The handle
        stays referenced on the dead backing so no GC close ever runs;
        it leaks until process exit, which is the price of fidelity."""
        with self._state_lock:
            self.crashed = True
            self.role = FOLLOWER
        self.hub.set_down(self.node_id, True)
        self.backing._stop.set()
        self._abandoned = self.backing

    def reopen(self) -> None:
        """Restart after a crash: recover the sqlite file (WAL replay),
        reload the durable epoch, rejoin as a follower."""
        self.backing = SqliteStore(self.path,
                                   poll_interval=self.poll_interval)
        with self._state_lock:
            self.epoch = int(self.backing.get_meta("epoch", "0"))
            self.role = FOLLOWER
            self.leader_id = None
            self.crashed = False
            self._lease_until = 0.0
        self._shipped_rv = self.backing.current_rv()
        self.hub.set_down(self.node_id, False)

    def close(self) -> None:
        if not self.crashed:
            self.backing.close()


class ReplicaSet:
    """Assembles N :class:`ReplicaNode`\\ s over one :class:`PeerHub`.

    Two drive modes:

    - **manual** (default; the analysis harnesses): no background
      threads; call :meth:`elect` to install a leader. The lease is long,
      so leadership is stable until explicitly taken over or fenced.
    - **auto** (``start()``; the chaos e2e): a seeded per-node ticker
      renews the leader's lease and campaigns on expiry with node-skewed
      jitter, so failover happens on its own within ~2 lease durations
      and the first winner is deterministic for a seed.
    """

    def __init__(self, n: int = 3, *, dir: str, lease_duration: float = 30.0,
                 retry_period: float = 0.1, poll_interval: float = 0.05,
                 seed: int = 0):
        self.hub = PeerHub()
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.node_ids = [f"n{i}" for i in range(n)]
        self.advertise: Dict[str, str] = {}
        self.leadership_log: List[Tuple[int, str]] = []
        self._log_lock = threading.Lock()
        self._seed = seed
        self._stop = threading.Event()
        self._tickers: List[threading.Thread] = []
        self.nodes: Dict[str, ReplicaNode] = {}
        for nid in self.node_ids:
            node = ReplicaNode(
                nid, os.path.join(dir, f"{nid}.db"), self.hub, self,
                lease_duration=lease_duration, poll_interval=poll_interval,
            )
            self.nodes[nid] = node
            self.hub.register(node)

    # -- bookkeeping ---------------------------------------------------------

    def _record_leader(self, epoch: int, node_id: str) -> None:
        with self._log_lock:
            self.leadership_log.append((epoch, node_id))

    def set_advertise(self, mapping: Dict[str, str]) -> None:
        """node id → advertised URL, once the HTTP servers know their
        ports; NotLeader hints then carry an address a client can dial."""
        self.advertise.update(mapping)

    # -- election ------------------------------------------------------------

    def elect(self, node_id: str) -> bool:
        """Manual, synchronous lease takeover by ``node_id``."""
        return self.nodes[node_id].campaign()

    def expire_leases(self) -> None:
        """Zero every live node's follower lease — the operator's forced-
        failover hand (≙ deleting the kube Lease object), and the manual-
        mode harnesses' fast-forward past the lease wait that auto mode
        serves out in real time. Votes stay epoch-gated, so safety (one
        leader per epoch) is untouched; only the liveness delay is
        skipped."""
        for node in self.nodes.values():
            with node._state_lock:
                node._lease_until = 0.0

    def leader(self) -> Optional[ReplicaNode]:
        best = None
        for node in self.nodes.values():
            with node._state_lock:
                if node.role == LEADER and not node.crashed:
                    if best is None or node.epoch > best.epoch:
                        best = node
        return best

    def wait_for_leader(self, timeout: float = 10.0
                        ) -> Optional[ReplicaNode]:
        deadline = _monotonic() + timeout
        while _monotonic() < deadline:
            node = self.leader()
            if node is not None:
                return node
            if self._stop.wait(0.02):
                return None
        return None

    def quiesce(self, timeout: float = 10.0) -> bool:
        """Wait until every live node has applied the leader's head rv
        (a leader heartbeat drags laggards); the deterministic read
        barrier harnesses use before diffing follower state."""
        deadline = _monotonic() + timeout
        while _monotonic() < deadline:
            lead = self.leader()
            if lead is not None:
                lead.renew()
                head = lead.backing.current_rv()
                live = [n for n in self.nodes.values() if not n.crashed]
                if all(n.backing.current_rv() == head for n in live):
                    return True
            if self._stop.wait(0.02):
                return False
        return False

    # -- auto mode -----------------------------------------------------------

    def start(self) -> "ReplicaSet":
        for i, nid in enumerate(self.node_ids):
            t = threading.Thread(
                target=self._tick_loop,
                args=(self.nodes[nid],
                      random.Random(f"{self._seed}:{nid}"), i),
                name=f"replica-tick-{nid}", daemon=True,
            )
            self._tickers.append(t)
            t.start()
        return self

    def _tick_loop(self, node: ReplicaNode, rng: random.Random,
                   index: int) -> None:
        while not self._stop.wait(self.retry_period):
            try:
                with node._state_lock:
                    crashed, role = node.crashed, node.role
                    expired = _monotonic() > node._lease_until
                if crashed:
                    continue
                if role == LEADER:
                    node.renew()
                elif expired:
                    # node-skewed jittered wait before campaigning keeps
                    # concurrent candidates from split-voting forever and
                    # makes the FIRST winner deterministic per seed
                    delay = index * self.retry_period / 2 + rng.uniform(
                        0, self.retry_period / 2
                    )
                    if self._stop.wait(delay):
                        return
                    with node._state_lock:
                        still = (not node.crashed
                                 and node.role == FOLLOWER
                                 and _monotonic() > node._lease_until)
                    if still:
                        node.campaign()
            except Exception:
                # a ticker must survive transient errors (a peer crashing
                # mid-RPC); a dead ticker would silently end failover
                log.debug("replica ticker error", exc_info=True)

    # -- fault surface -------------------------------------------------------

    def crash(self, node_id: str) -> None:
        self.nodes[node_id].crash()

    def restart(self, node_id: str) -> None:
        self.nodes[node_id].reopen()

    # -- status / lifecycle --------------------------------------------------

    def status(self) -> List[Dict[str, Any]]:
        return [self.nodes[nid].replica_status() for nid in self.node_ids]

    def client(self, read_from: Optional[str] = None) -> "ReplicaClient":
        return ReplicaClient(self, read_from=read_from)

    def stop(self) -> None:
        self._stop.set()
        for t in self._tickers:
            t.join(timeout=2.0)
        for node in self.nodes.values():
            node.close()


class NodeTarget:
    """ChaosController process-target adapter for an in-process replica
    node: ``kill`` is the SIGKILL-equivalent hard crash, ``restart``
    reopens from the same files. ``node_id=None`` resolves 'the current
    leader' at fire time — the scripted leader-kill."""

    def __init__(self, rset: ReplicaSet, node_id: Optional[str] = None):
        self.rset = rset
        self.node_id = node_id
        self.killed: Optional[str] = None

    def _resolve(self) -> str:
        if self.node_id is not None:
            return self.node_id
        lead = self.rset.leader()
        if lead is None:
            raise RuntimeError("no leader to target")
        return lead.node_id

    def kill(self) -> None:
        self.killed = self._resolve()
        self.rset.crash(self.killed)

    def term(self) -> None:
        self.kill()  # a store node has no graceful-drain distinction

    def restart(self) -> None:
        target = self.killed or self._resolve()
        self.rset.restart(target)


class ReplicaClient:
    """The in-process failover client: same duck-typed store surface,
    mutations routed to the leased leader (following NotLeader hints with
    bounded jittered backoff — the socketless twin of HttpStoreClient's
    multi-endpoint rotation), reads and watch fan-out served by a
    follower, which is exactly the replica set's read contract: lag is
    legal, rv regression is not."""

    def __init__(self, rset: ReplicaSet, *, read_from: Optional[str] = None,
                 mutation_attempts: int = 12, backoff: float = 0.05):
        self._set = rset
        self._read_from = read_from
        self._attempts = mutation_attempts
        self._backoff = backoff
        self._rng = random.Random(f"client:{rset._seed}")
        self._guess: Optional[ReplicaNode] = None
        # per-queue owner node: stop_watch must unregister a queue from
        # the node that issued it, not whichever node served the LATEST
        # watch() call (a silently un-stopped queue fills forever)
        self._watch_nodes: Dict[int, ReplicaNode] = {}
        self._stop = threading.Event()

    # -- routing -------------------------------------------------------------

    def _read_node(self) -> ReplicaNode:
        if self._read_from is not None:
            node = self._set.nodes[self._read_from]
            if not node.crashed:
                return node
        # failover reads pick the MOST CAUGHT-UP live node (leader
        # included), not merely the first live follower: falling back
        # from a crashed pinned node to a lagging follower could
        # un-observe an acked write this client already read — the rv
        # regression the follower-read contract forbids (per-node reads
        # stay monotone; cross-node failover must not go backwards
        # through the acked history)
        live = [n for n in self._set.nodes.values() if not n.crashed]
        if not live:
            raise PeerUnreachable("no live replica to read from")
        # ties (the healthy steady state) still prefer a follower —
        # spreading reads off the leader is the replica set's point
        return max(live, key=lambda n: (n.backing.current_rv(),
                                        n.role != LEADER))

    def _mutate(self, fn: Callable[[ReplicaNode], Any]) -> Any:
        """Route a mutation to the leader, re-resolving on NotLeader /
        unreachable with bounded jittered backoff. Only DEFINITE
        failures are retried; ReplicationUnavailable (indeterminate)
        propagates — the caller owns the re-read."""
        delay = self._backoff
        last: Optional[Exception] = None
        for _ in range(self._attempts):
            node = self._guess
            if node is None or node.crashed:
                node = self._set.leader()
            if node is not None and not node.crashed:
                try:
                    out = fn(node)
                    self._guess = node
                    return out
                except NotLeader as e:
                    last = e
                    hint = e.leader
                    self._guess = next(
                        (n for n in self._set.nodes.values()
                         if n.node_id == hint and not n.crashed),
                        None,
                    )
                except PeerUnreachable as e:
                    last = e
                    self._guess = None
            jittered = delay * (1 + self._rng.uniform(0, 0.25))
            if self._stop.wait(jittered):
                break
            delay = min(delay * 2, 1.0)
        raise last if last is not None else PeerUnreachable(
            "no replica leader reachable"
        )

    def replica_status(self) -> List[Dict[str, Any]]:
        return self._set.status()

    # -- duck-typed store surface --------------------------------------------

    def create(self, obj: Any) -> Any:
        return self._mutate(lambda n: n.create(obj))

    def update(self, obj: Any, force: bool = False) -> Any:
        return self._mutate(lambda n: n.update(obj, force))

    def patch(self, kind: str, namespace: str, name: str, patch: Any, *,
              subresource: Optional[str] = None) -> Any:
        return self._mutate(
            lambda n: n.patch(kind, namespace, name, patch,
                              subresource=subresource)
        )

    def patch_batch(self, items: List[Dict[str, Any]]) -> List[Any]:
        return self._mutate(lambda n: n.patch_batch(items))

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        return self._mutate(lambda n: n.delete(kind, namespace, name))

    def try_delete(self, kind: str, namespace: str, name: str
                   ) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except KeyError:  # NotFound subclasses KeyError
            return None

    def get(self, kind: str, namespace: str, name: str) -> Any:
        return self._read_node().get(kind, namespace, name)

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        return self._read_node().try_get(kind, namespace, name)

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[Any]:
        return self._read_node().list(kind, namespace, selector)

    def current_rv(self) -> int:
        return self._read_node().current_rv()

    def watch(self, kind: Optional[str] = None):
        node = self._read_node()
        q = node.watch(kind)
        self._watch_nodes[id(q)] = node
        return q

    def stop_watch(self, q) -> None:
        node = self._watch_nodes.pop(id(q), None)
        if node is not None:
            node.stop_watch(q)

    def add_relist_listener(self, cb) -> None:
        self._read_node().add_relist_listener(cb)

    def close(self) -> None:
        self._stop.set()
