"""Replicated HA store: a 3-node log-shipping replica set over SqliteStore.

The store was the control plane's last single point of failure (ROADMAP
item 1): PR 3 proved single-node crash-recovery, nothing more. This module
is the kube-apiserver/etcd split's missing half — a leased leader accepts
all mutations and synchronously ships the committed-op WAL (every
``SqliteStore._txn`` commit is already a log row carrying the object at
its rv) to followers, **acking a write only after a majority has durably
applied it**. Followers serve reads and watch fan-out from their own
sqlite files (listers/informers may lag, never regress rv); a new leader
is elected by quorum lease takeover with log-tail reconciliation.

Protocol, in five rules:

1. **Epochs are votes.** A node's durable ``epoch`` (replica_meta, via the
   same ``_txn`` seam every write rides) only ever increases, and adopting
   an epoch IS granting that epoch's single vote. Majorities intersect, so
   **at most one leader exists per epoch** — the chaos e2e asserts exactly
   that from the leadership log.
2. **Leases fence.** A follower refuses votes while its current leader's
   lease (refreshed by every append/heartbeat) is still running, so a
   live leader cannot be deposed by a flaky candidate; a leader that
   cannot renew against a majority steps down at its own (shorter) local
   deadline before any grantor's lease can expire.
3. **Commit = majority-durable.** The leader commits locally (its sqlite
   IS one of the copies), ships the new log rows to every reachable
   follower, and acks the client only when ``majority`` copies (itself
   included) have applied. Shipping to ALL reachable followers before
   returning is what makes follower reads read-your-writes on a healthy
   set — the property the differential fuzzer leans on.
4. **Election reconciles tails.** A winning candidate adopts the highest
   applied rv among its granting quorum (pulling the missing tail, or a
   full snapshot when the tail was trimmed). Any ACKED write is on a
   majority; any quorum intersects that majority; therefore the new
   leader's history contains every acked write — the no-acked-write-lost
   invariant.
5. **Divergent suffixes truncate.** Entries are shipped with the previous
   entry's content hash; a follower whose same-rv history hashes
   differently (it holds a dead epoch's unacked suffix — e.g. the old
   leader's local commit that never reached a majority) resyncs from a
   leader snapshot, wiping the suffix. A write the leader definitively
   rejected is therefore never resurrected; a write that died
   *indeterminately* (:class:`ReplicationUnavailable` — the leader lost
   its majority mid-ship) may surface or vanish, exactly like an
   apiserver timeout, and is documented as such.

Deployment shape: each node's duck-typed surface can sit behind its own
``StoreServer``; follower mutations raise :class:`NotLeader` (421 on the
wire, with a leader hint) and ``HttpStoreClient`` rotates/redirects.
In-process, :class:`ReplicaClient` is the same failover client without
the sockets — it is what the analysis gates (storecheck / linearize /
crashpoints) drive, the replica set being just another duck-typed
backend to them.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import random
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.sqlite_store import (
    LogTruncated,
    SqliteStore,
    entry_hash,
)
from mpi_operator_tpu.machinery.store import (
    NotLeader,
    ReplicationUnavailable,
)
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.replica")

LEADER = "leader"
FOLLOWER = "follower"


class PeerUnreachable(ConnectionError):
    """The transport could not deliver (node down / link partitioned)."""


class UnknownTransfer(KeyError):
    """A snapshot chunk referenced a transfer the sender no longer holds
    (sender restarted, or the bounded outbox evicted it): the puller must
    restart from a fresh offer — resuming blind would splice two different
    snapshots' bytes."""


# size bounds for one shipped append_entries batch: a cold joiner's full
# history must arrive as many bounded requests, not one body the wire's
# 8 MiB request cap rejects (which would permanently wedge its catch-up).
# BOTH bounds apply — count alone is not enough: 512 × 20 KiB manifests
# is already a 10 MiB body. A single entry larger than the byte budget
# ships alone (entries are atomic; object bodies are themselves capped
# by the same 8 MiB client-request limit, so a lone entry always fits).
SHIP_BATCH_ENTRIES = 512
SHIP_BATCH_BYTES = 2 << 20

# chunked snapshot transfer: size-bounded chunks (well under the wire's
# request cap), whole-payload sha256 verified before the atomic
# load_snapshot, resumable at chunk granularity after a dropped connection
SNAPSHOT_CHUNK_BYTES = 256 << 10

# after a ship attempt finds a peer unreachable, skip shipping to it for
# this long (the next heartbeat/ship after the window re-probes): without
# the window a DEAD peer taxes EVERY write the full dial-timeout+retry
# cost INSIDE the serialized ship gate — measured 7→83 ms per ship (a
# ~12 writes/s ceiling) in the torture run after the leader kill
PEER_DOWN_BACKOFF = 1.0

# how many of a fresh reign's first majority-acked ships emit a
# `replica.reign` bridge span (trace continuity: the bridge lives in the
# winning election's trace with its parent edge in the shipped write's
# trace, so `ctl trace --last-incident` connects write → ship → election
# → the first post-failover reconciles whose writes ride those ships)
REIGN_BRIDGE_SHIPS = 64


class StaleEpoch(RuntimeError):
    """An RPC arrived from a dead epoch: the sender has been superseded
    and must step down (the fencing signal)."""

    def __init__(self, current_epoch: int):
        super().__init__(f"superseded by epoch {current_epoch}")
        self.current_epoch = current_epoch


class PeerHub:
    """In-process replica transport with fault injection: per-node down
    flags (SIGKILL semantics) and symmetric pairwise partitions — the
    fabric seam ChaosScript ``partition`` actions drive. Calls are
    synchronous method dispatch; an unreachable destination raises
    :class:`PeerUnreachable` exactly where a socket would ECONNREFUSED."""

    def __init__(self):
        self._lock = threading.Lock()
        self._nodes: Dict[str, "ReplicaNode"] = {}
        self._down: Dict[str, bool] = {}
        self._cuts: set = set()  # frozenset({a, b}) pairs

    def register(self, node: "ReplicaNode") -> None:
        with self._lock:
            self._nodes[node.node_id] = node
            self._down[node.node_id] = False

    def set_down(self, node_id: str, down: bool) -> None:
        with self._lock:
            self._down[node_id] = down
            nodes = list(self._nodes.values()) if not down else []
        # a REVIVED node is immediately shippable again: clear every
        # peer's down-window for it so the manual-mode harnesses' very
        # next synchronous renew() reaches it (the hub lock is released
        # first — a shipping node holds its ship lock while briefly
        # taking ours, so nesting the other way would deadlock)
        for node in nodes:
            node._clear_peer_down(node_id)

    # -- the chaos fabric surface (ChaosController(fabric=hub)) -------------

    def partition(self, a: str, b: str) -> None:
        """Blackhole BOTH directions between two named endpoints."""
        with self._lock:
            if a not in self._nodes or b not in self._nodes:
                raise KeyError(f"unknown partition endpoint in ({a!r}, {b!r})")
            self._cuts.add(frozenset((a, b)))

    def heal(self, a: str, b: str) -> None:
        with self._lock:
            self._cuts.discard(frozenset((a, b)))
            pair = [n for nid, n in self._nodes.items() if nid in (a, b)]
        for node in pair:  # healed link: re-probe without the window
            for other in (a, b):
                if other != node.node_id:
                    node._clear_peer_down(other)

    def heal_all(self) -> None:
        with self._lock:
            self._cuts.clear()
            nodes = list(self._nodes.values())
        for node in nodes:
            for other in nodes:
                if other is not node:
                    node._clear_peer_down(other.node_id)

    def call(self, src: str, dst: str, method: str, *args) -> Any:
        with self._lock:
            if self._down.get(dst, True) or self._down.get(src, False):
                raise PeerUnreachable(f"{dst} is down")
            if frozenset((src, dst)) in self._cuts:
                raise PeerUnreachable(f"{src}<->{dst} partitioned")
            node = self._nodes[dst]
        # dispatch OUTSIDE the hub lock: a handler may itself call peers
        return getattr(node, method)(*args)


def _monotonic() -> float:
    return time.monotonic()


def tick_node(node: "ReplicaNode", rng: random.Random, index: int,
              retry_period: float, stop: threading.Event) -> None:
    """ONE auto-mode tick for one node: a leader renews its lease, a
    follower whose leader's lease expired campaigns after a node-skewed
    jittered wait (keeps concurrent candidates from split-voting forever
    and makes the FIRST winner deterministic per seed). Shared by
    :meth:`ReplicaSet._tick_loop` (in-process auto mode) and the wire
    deployment's per-process ticker (machinery/replica_wire.py)."""
    with node._state_lock:
        crashed, role = node.crashed, node.role
        expired = _monotonic() > node._lease_until
    if crashed:
        return
    if role == LEADER:
        node.renew()
    elif expired:
        delay = index * retry_period / 2 + rng.uniform(0, retry_period / 2)
        if stop.wait(delay):
            return
        with node._state_lock:
            still = (not node.crashed
                     and node.role == FOLLOWER
                     and _monotonic() > node._lease_until)
        if still:
            node.campaign()


class ReplicaNode:
    """One replica-set member: a SqliteStore plus the replication role.

    Duck-typed store surface: reads/watches serve locally on any role;
    mutations require the lease and run commit-then-ship-then-ack under
    one gate so ship order equals rv order. RPC handler methods
    (request_vote / append_entries / fetch_entries / install_snapshot /
    replica_status) are invoked by peers through the hub.
    """

    def __init__(self, node_id: str, path: str, hub: PeerHub, rset:
                 "ReplicaSet", *, lease_duration: float,
                 poll_interval: float = 0.05,
                 snapshot_chunk_bytes: int = SNAPSHOT_CHUNK_BYTES,
                 ship_batch_entries: int = SHIP_BATCH_ENTRIES,
                 ship_batch_bytes: int = SHIP_BATCH_BYTES):
        self.node_id = node_id
        self.path = path
        self.hub = hub
        self.rset = rset
        self.lease_duration = lease_duration
        self.poll_interval = poll_interval
        self.snapshot_chunk_bytes = snapshot_chunk_bytes
        self.ship_batch_entries = ship_batch_entries
        self.ship_batch_bytes = ship_batch_bytes
        self.backing = SqliteStore(path, poll_interval=poll_interval)
        # durable election state: adopting an epoch IS this node's one
        # vote in it (rule 1); survives crash/restart via replica_meta
        self.epoch = int(self.backing.get_meta("epoch", "0"))
        self._state_lock = threading.RLock()
        self.role = FOLLOWER
        self.leader_id: Optional[str] = None
        self.crashed = False
        # follower: how long the current leader's lease runs on MY clock;
        # leader: my own (stricter) renew deadline
        self._lease_until = 0.0
        self._lease_deadline = 0.0
        # leader ship cursor + per-peer applied rv (the lag metric)
        self._ship_lock = threading.Lock()
        self._shipped_rv = self.backing.current_rv()
        self._peer_rv: Dict[str, int] = {}
        # peer → monotonic deadline until which ships SKIP it (set on an
        # unreachable attempt, cleared on any success); guarded by
        # _ship_lock like the cursor it modulates
        self.peer_down_backoff = PEER_DOWN_BACKOFF
        self._peer_down_until: Dict[str, float] = {}
        # peers needing a full snapshot resync (divergent suffix / log
        # truncated): the SHIP path only MARKS them (the write degrades
        # to majority-only), renew() runs the transfer OUTSIDE the ship
        # gate — a multi-second wire transfer inside the gate would block
        # every write AND the heartbeats, expiring the healthy peers'
        # leases and dethroning the leader mid-join. Guarded by
        # _ship_lock; _resync_active keeps the transfer single-flight.
        self._resync_pending: set = set()
        self._resync_active: set = set()
        # serializes the WHOLE fence-check→apply window of incoming
        # append_entries/install_snapshot: without it a stale leader's
        # delayed append could pass the epoch fence, stall, and then
        # interleave its dead-epoch rows into a newer leader's apply
        # (duplicate-rv IntegrityError or a gapped follower history)
        self._apply_lock = threading.Lock()
        # chunked snapshot outbox: transfer id → encoded snapshot bytes.
        # Bounded (the newest few transfers); an evicted/unknown id raises
        # UnknownTransfer and the puller restarts from a fresh offer.
        self._transfer_lock = threading.Lock()
        self._transfers: Dict[str, bytes] = {}
        # trace continuity across failover: the span context of the last
        # NON-EMPTY ship applied here (the write whose history this node
        # would extend if elected) anchors this node's election span, and
        # a fresh reign's first ships carry a bridge back to the election
        self._last_ship_ctx: Optional[Tuple[str, str]] = None
        self._reign_ctx: Optional[Tuple[str, str]] = None
        self._reign_bridges = REIGN_BRIDGE_SHIPS

    # -- small helpers -------------------------------------------------------

    @property
    def peers(self) -> List[str]:
        return [n for n in self.rset.node_ids if n != self.node_id]

    @property
    def majority(self) -> int:
        return len(self.rset.node_ids) // 2 + 1

    def _clear_peer_down(self, peer: str) -> None:
        """Forget a peer's unreachable-window (it revived / the link
        healed): the next ship reaches it immediately."""
        with self._ship_lock:
            self._peer_down_until.pop(peer, None)

    def _leader_hint(self) -> Optional[str]:
        with self._state_lock:
            lid = self.node_id if self.role == LEADER else self.leader_id
        return self.rset.advertise.get(lid, lid) if lid else None

    def _adopt_epoch(self, epoch: int) -> None:
        """Durably advance to ``epoch`` (caller holds _state_lock)."""
        self.backing.set_meta("epoch", str(epoch))
        self.epoch = epoch

    def _step_down(self, why: str) -> None:
        with self._state_lock:
            if self.role == LEADER:
                log.info("%s: stepping down (%s)", self.node_id, why)
            self.role = FOLLOWER
            # hold off on campaigning for a full lease: peers granted the
            # superseding/surviving side time to establish itself
            self._lease_until = _monotonic() + self.lease_duration

    def _require_leader(self) -> int:
        """Validate leadership and return THE REIGN'S EPOCH, atomically:
        everything shipped on behalf of this check must be stamped with
        exactly this epoch — re-reading self.epoch at ship time would
        let a leader deposed mid-write ship its entry as the NEW epoch's
        traffic, sailing past the StaleEpoch fence."""
        with self._state_lock:
            if self.crashed:
                raise PeerUnreachable(f"{self.node_id} is down")
            if self.role != LEADER:
                raise NotLeader(
                    f"replica {self.node_id} is a follower; mutations go "
                    f"to the leased leader",
                    leader=self._leader_hint(),
                )
            if _monotonic() > self._lease_deadline:
                raise NotLeader(
                    f"replica {self.node_id}'s lease expired; awaiting "
                    f"re-election",
                    leader=None,
                )
            return self.epoch

    # -- replication (leader side) ------------------------------------------

    def _leader_write(self, fn: Callable[[], Any]) -> Any:
        """Commit locally, ship the new log rows, ack on majority. One
        gate serializes writers so the ship stream is exactly the commit
        stream; store errors (Conflict/NotFound/...) raise before any
        commit and ship nothing — they stay DEFINITE failures."""
        # is this write part of a LARGER trace (a traced client sent a
        # traceparent, or an in-process component holds an app span)?
        # Only such writes spend the reign-bridge budget: bridging an
        # untraced root write (a bare CLI create) connects the election
        # to nothing, and a burst of them after failover would exhaust
        # the budget before the first post-failover reconcile ships.
        cur = trace.TRACER.current_span()
        traced = cur is not None and (
            cur.parent_id is not None
            or getattr(cur, "name", "store.request") != "store.request"
        )
        with self._ship_lock:
            epoch = self._require_leader()
            result = fn()
            # the ship span covers commit-to-majority-ack (the HA write
            # tax); its duration lands in the ship-latency histogram at
            # close. Nested under whatever span the writer holds (e.g.
            # the store server's request span), so `ctl trace` shows the
            # replication hop inside the write that paid for it.
            t0 = time.perf_counter()
            with trace.start_span(
                "replica.ship",
                attrs={"node": self.node_id, "epoch": epoch},
            ):
                self._replicate(epoch, traced)
            metrics.replication_ship_latency.observe(
                time.perf_counter() - t0
            )
            return result

    def _replicate(self, epoch: int, traced: bool = False) -> None:
        tail = self.backing.log_tail(self._shipped_rv)
        if not tail:
            # an empty tail after fn() is normally just an all-failure
            # patch_batch (nothing committed). But if the REIGN advanced
            # mid-write, a new leader's resync may have truncated the
            # just-committed entry out of our local history before we
            # could ship it — returning success would silently ack a
            # write that exists nowhere. History rewrites always ride an
            # epoch advance, so the reign check is the exact detector.
            with self._state_lock:
                if self.epoch != epoch:
                    raise ReplicationUnavailable(
                        f"superseded by epoch {self.epoch} mid-write: "
                        f"the local commit may have been truncated by "
                        f"the new leader's history — outcome "
                        f"INDETERMINATE, re-read before retrying"
                    )
            return
        acks = 1  # the local sqlite commit is copy #1
        for peer in self.peers:
            if self._ship_to(peer, epoch, self._shipped_rv, tail):
                acks += 1
        self._shipped_rv = tail[-1]["rv"]
        self._update_lag()
        if acks >= self.majority:
            with self._state_lock:
                # a majority-acked ship doubles as a lease renewal — but
                # only for the reign that shipped it: a leader deposed
                # mid-write must not resurrect its deadline
                if self.role == LEADER and self.epoch == epoch:
                    self._lease_deadline = max(
                        self._lease_deadline,
                        _monotonic() + self.lease_duration,
                    )
            if (traced and self._reign_ctx is not None
                    and self._reign_bridges > 0):
                self._reign_bridges -= 1
                # trace bridge: lives in the WINNING ELECTION's trace,
                # parent edge = this write's ship span — the edge that
                # makes write → ship → election → first post-failover
                # reconcile ONE connected component for `ctl trace
                # --last-incident` (each bridge closes immediately; only
                # the first REIGN_BRIDGE_SHIPS ships of a reign pay it)
                with trace.start_span(
                    "replica.reign", trace_id=self._reign_ctx[0],
                    attrs={"node": self.node_id, "epoch": epoch},
                ):
                    pass
            return
        self._step_down("write could not reach a majority")
        raise ReplicationUnavailable(
            f"write committed on {acks}/{len(self.rset.node_ids)} replicas "
            f"(majority {self.majority} unreachable): outcome INDETERMINATE "
            f"— re-read before retrying"
        )

    def _append_to(self, peer: str, epoch: int, prev_rv: int,
                   entries: List[Dict[str, Any]]) -> Dict[str, Any]:
        """append_entries in size-bounded slices: the wire caps request
        bodies (8 MiB), so a cold joiner's full-history tail must arrive
        as many bounded appends — one giant request would be rejected and
        permanently wedge its catch-up. Slices are bounded by COUNT and
        by BYTES (count alone is not enough: 512 × 20 KiB manifests is a
        10 MiB body). Each slice's prev-hash comes from the slice before
        it (the entries are in hand), keeping the divergence check
        intact at every boundary."""
        prev_hash = self.backing.tail_hash(prev_rv)
        if not entries:
            return self.hub.call(
                self.node_id, peer, "append_entries",
                epoch, self.node_id, prev_rv, prev_hash, [],
            )
        res: Dict[str, Any] = {}
        i = 0
        while i < len(entries):
            batch, nbytes = [], 0
            while i < len(entries) and len(batch) < self.ship_batch_entries:
                cost = len(entries[i]["data"]) + 256  # rough envelope
                if batch and nbytes + cost > self.ship_batch_bytes:
                    break  # an over-budget entry ships ALONE, never split
                batch.append(entries[i])
                nbytes += cost
                i += 1
            res = self.hub.call(
                self.node_id, peer, "append_entries",
                epoch, self.node_id, prev_rv, prev_hash, batch,
            )
            applied = res.get("applied")
            if applied is None or applied < batch[-1]["rv"]:
                return res  # behind/divergent: the caller resolves it
            prev_rv = batch[-1]["rv"]
            prev_hash = entry_hash(batch[-1])
        return res

    def _ship_to(self, peer: str, epoch: int, prev_rv: int,
                 entries: List[Dict[str, Any]]) -> bool:
        """Push a tail to one follower, walking it through lag catch-up
        (``behind``) and divergent-suffix truncation (``divergent`` →
        chunked snapshot install). Returns True when the follower's
        applied rv reaches the tail's end.

        A peer that was unreachable moments ago is SKIPPED until its
        down-window lapses (the next heartbeat re-probes): a dead peer
        must cost the write path one probe per window, not a dial
        timeout per write inside the serialized ship gate."""
        if self._peer_down_until.get(peer, 0.0) > _monotonic():
            return False
        target_rv = entries[-1]["rv"] if entries else prev_rv
        try:
            for _ in range(4):  # behind/divergent round-trips, bounded
                res = self._append_to(peer, epoch, prev_rv, entries)
                applied = res.get("applied")
                if applied is not None and applied >= target_rv:
                    self._peer_rv[peer] = applied
                    self._peer_down_until.pop(peer, None)
                    return True
                if "behind" in res:
                    prev_rv = res["behind"]
                elif res.get("divergent"):
                    # divergent suffix / truncated log: the follower
                    # needs a FULL snapshot resync. Never run it here —
                    # the caller holds the serialized ship gate, and a
                    # multi-second wire transfer inside it would stall
                    # every write and heartbeat (expiring healthy peers'
                    # leases → a spurious failover per cold join). Mark
                    # it; renew() transfers outside the gate; this ship
                    # degrades to majority-only.
                    self._resync_pending.add(peer)
                    return False
                else:
                    return False
                try:
                    entries = self.backing.log_tail(prev_rv)
                except LogTruncated:
                    prev_rv = -1  # force the snapshot path next loop
                    entries = []
                    continue
            return False
        except PeerUnreachable:
            self._peer_down_until[peer] = (
                _monotonic() + self.peer_down_backoff
            )
            return False
        except StaleEpoch as e:
            self._step_down(f"fenced by epoch {e.current_epoch}")
            raise ReplicationUnavailable(
                f"superseded by epoch {e.current_epoch} mid-ship: outcome "
                f"INDETERMINATE — re-read before retrying"
            ) from None

    def _update_lag(self) -> None:
        head = self.backing.current_rv()
        for peer, rv in self._peer_rv.items():
            metrics.store_replication_lag.set(
                max(0, head - rv), follower=peer,
            )

    def _heartbeat(self, epoch: int) -> int:
        """Empty append to every peer: refreshes their leases, drags
        laggards up to the ship cursor. Returns reachable copies (self
        included). MUST run under _ship_lock: racing a concurrent
        _replicate on the shared ship cursor would read it mid-advance
        and misdiagnose a healthy follower as divergent (a spurious
        snapshot resync) or double-apply the in-flight rows. ``epoch``
        is the reign being renewed, captured atomically with the role
        check — never re-read at ship time."""
        acks = 1
        for peer in self.peers:
            try:
                if self._ship_to(peer, epoch, self._shipped_rv, []):
                    acks += 1
            except ReplicationUnavailable:
                return acks  # fenced mid-heartbeat: already stepped down
        self._update_lag()
        return acks

    def renew(self) -> None:
        """Leader tick: heartbeat; renew the local deadline on majority,
        step down once it passes without one; then run any pending
        snapshot resyncs OUTSIDE the ship gate — on a worker joined for
        a BOUNDED slice of the lease: in-process transfers finish inside
        the join (the manual-mode harnesses still converge right after
        renew()), while a slow wire transfer DETACHES so the next ticks
        keep heartbeating — an idle cluster must not let one long
        cold-join starve the healthy follower's lease into a spurious
        election that would discard the transfer (single-flight via
        _resync_active either way)."""
        with self._state_lock:
            if self.role != LEADER or self.crashed:
                return
            epoch = self.epoch
        with self._ship_lock:
            acks = self._heartbeat(epoch)
            pending = [p for p in self._resync_pending
                       if p not in self._resync_active]
            self._resync_active.update(pending)
        now = _monotonic()
        with self._state_lock:
            if self.role != LEADER or self.epoch != epoch:
                with self._ship_lock:
                    self._resync_active.difference_update(pending)
                return
            if acks >= self.majority:
                self._lease_deadline = max(
                    self._lease_deadline, now + self.lease_duration
                )
            elif now > self._lease_deadline:
                self._step_down("lease renewal lost its majority")
        if pending:
            worker = threading.Thread(
                target=self._run_resyncs, args=(pending, epoch),
                name=f"replica-resync-{self.node_id}", daemon=True,
            )
            worker.start()
            worker.join(min(2.0, self.lease_duration / 4))

    def _run_resyncs(self, pending: List[str], epoch: int) -> None:
        try:
            for peer in pending:
                self._resync_peer(peer, epoch)
        finally:
            with self._ship_lock:
                self._resync_active.difference_update(pending)

    def _resync_peer(self, peer: str, epoch: int) -> None:
        """Full snapshot resync of one divergent/truncated follower, off
        the ship gate: offer a snapshot, let the follower PULL it in
        chunks (it dials back through its own fabric), record the
        result. Failure leaves the peer pending — the next renew
        retries; concurrent writes meanwhile ack on the majority and
        their ships to this peer keep answering divergent (benign: the
        set already dedups)."""
        with self._ship_lock:
            if self._peer_down_until.get(peer, 0.0) > _monotonic():
                return  # unreachable moments ago; stay pending
        offer = self.snapshot_offer()
        try:
            res = self.hub.call(
                self.node_id, peer, "install_snapshot",
                epoch, self.node_id, {"offer": offer},
            )
        except PeerUnreachable:
            with self._ship_lock:
                self._peer_down_until[peer] = (
                    _monotonic() + self.peer_down_backoff
                )
            return
        except StaleEpoch as e:
            self._step_down(f"fenced by epoch {e.current_epoch} mid-resync")
            with self._ship_lock:
                self._resync_pending.discard(peer)
            return
        applied = res.get("applied")
        with self._ship_lock:
            if applied is not None:
                self._peer_rv[peer] = max(self._peer_rv.get(peer, 0),
                                          applied)
                self._resync_pending.discard(peer)
                self._peer_down_until.pop(peer, None)

    # -- election ------------------------------------------------------------

    def campaign(self) -> bool:
        """Traced wrapper over :meth:`_campaign`: a WON election's
        campaign-start-to-leadership time is the failover duration PERF
        round 8 clocked by hand — now a histogram + a ``replica.election``
        span (`ctl trace --last-incident` anchors on it)."""
        t0 = _monotonic()
        # anchor the election on the last applied ship's span: the write
        # whose history this candidate extends is the election's causal
        # parent, so the failover trace reads write → ship → election
        anchor = self._last_ship_ctx
        with trace.start_span(
            "replica.election", parent=anchor,
            attrs={"node": self.node_id},
        ) as sp:
            won = self._campaign()
            sp.set_attr("won", won)
            if won:
                sp.set_attr("epoch", self.epoch)
                ctx = sp.context()
                if ctx is not None:
                    self._reign_ctx = (ctx.trace_id, ctx.span_id)
                    self._reign_bridges = REIGN_BRIDGE_SHIPS
        if won:
            metrics.failover_duration.observe(_monotonic() - t0)
        return won

    def _campaign(self) -> bool:
        """Try to take the lease: adopt epoch+1 (the self-vote), gather
        grants, reconcile the log tail to the quorum max (rule 4), then
        lead. A refusal carries the refuser's epoch; a candidate whose
        epoch lagged the quorum (a healed ex-minority node) adopts the
        learned epoch and retries once ABOVE it — without this, a node
        that slept through elections needs two external campaign calls
        to even be eligible. Returns True on a won election."""
        votes = 0
        tails: Dict[str, int] = {}
        for _attempt in (0, 1, 2):
            with self._state_lock:
                if self.crashed:
                    return False
                if self.role == LEADER:
                    return True
                target = self.epoch + 1
            # PRE-VOTE (Raft §9.6): ask whether a majority WOULD grant
            # before durably adopting the new epoch. Without it, a healed
            # minority node's doomed campaign leaves a higher epoch
            # behind, and the live leader's next ship to it gets
            # StaleEpoch-fenced — an indeterminate write + a spurious
            # failover on every partition heal, the exact disruption
            # rule 2 promises cannot happen.
            would, behind_by = 1, 0
            for peer in self.peers:
                try:
                    res = self.hub.call(self.node_id, peer, "request_vote",
                                        target, self.node_id, True)
                except PeerUnreachable:
                    continue
                if res.get("granted"):
                    would += 1
                else:
                    behind_by = max(behind_by, res.get("epoch", 0))
            if would < self.majority:
                if behind_by < target:
                    return False  # refused on leases: genuinely doomed
                with self._state_lock:
                    if behind_by > self.epoch:
                        # our epoch lagged the quorum (a healed minority
                        # node): LEARN it — adopting an epoch that
                        # already exists elsewhere fences nobody — and
                        # retry above it
                        self._adopt_epoch(behind_by)
                continue
            with self._state_lock:
                if self.crashed or self.role == LEADER:
                    return self.role == LEADER
                target = self.epoch + 1
                self._adopt_epoch(target)  # the durable self-vote
                self.leader_id = None
            votes, tails, behind_by = 1, {}, 0
            for peer in self.peers:
                try:
                    res = self.hub.call(self.node_id, peer, "request_vote",
                                        target, self.node_id)
                except PeerUnreachable:
                    continue
                if res.get("granted"):
                    votes += 1
                    tails[peer] = res["rv"]
                else:
                    behind_by = max(behind_by, res.get("epoch", 0))
            if votes >= self.majority:
                break
            if behind_by < target:
                return False  # refused on leases, not on a stale epoch
            with self._state_lock:
                if behind_by > self.epoch:
                    self._adopt_epoch(behind_by)  # learn, retry above it
        if votes < self.majority:
            return False
        my_rv = self.backing.current_rv()
        best = max(tails, key=tails.get, default=None)
        if best is not None and (tails[best] > 0 or my_rv > 0):
            # reconcile against the quorum max at the COMMON history
            # point — behind, EQUAL, or even when this candidate is
            # numerically AHEAD: rv comparison alone cannot distinguish
            # the grantor's acked history from a same-or-higher-numbered
            # dead-epoch suffix (an ex-leader's unacked local commits —
            # a partitioned patch_batch leaves SEVERAL). The catch-up
            # carries our hash at min(rv)s, so the grantor answers with
            # entries (in sync / we're behind), or a snapshot that
            # TRUNCATES our divergent suffix before we lead. Entries
            # above the quorum max are provably unacked (an acked write
            # is on a majority, which every quorum intersects), so
            # truncating them is always legal; skipping the check would
            # let the rejoining ex-leader win and then snapshot ACKED
            # writes OFF the quorum — the exact inversion of rule 4.
            self._catch_up_from(best, min(my_rv, tails[best]))
        with self._ship_lock:
            # reset the ship cursor BEFORE taking leadership: a client
            # write slipping in between the role flip and a later reset
            # would ship from a stale cursor
            self._shipped_rv = self.backing.current_rv()
            self._peer_rv = {}
            self._resync_pending.clear()  # the new reign re-evaluates
        with self._state_lock:
            if self.epoch != target:
                return False  # a higher epoch appeared mid-election
            self.role = LEADER
            self.leader_id = self.node_id
            self._lease_deadline = _monotonic() + self.lease_duration
        metrics.store_replication_failovers.inc()
        self.rset._record_leader(target, self.node_id)
        log.info("%s: leading epoch %d at rv %d", self.node_id, target,
                 self._shipped_rv)
        with self._ship_lock:
            # establish leases + drag laggards up NOW, as the new reign
            self._heartbeat(target)
        return True

    def _catch_up_from(self, peer: str, after_rv: int) -> None:
        res = self.hub.call(
            self.node_id, peer, "fetch_entries",
            after_rv, self.backing.tail_hash(after_rv),
        )
        if "snapshot_offer" in res:
            self.backing.load_snapshot(
                self._pull_snapshot(peer, res["snapshot_offer"])
            )
        elif "snapshot" in res:  # inline snapshot (direct-call harnesses)
            self.backing.load_snapshot(res["snapshot"])
        else:
            self.backing.apply_replicated(res["entries"])

    # -- chunked snapshot transfer (the cold-join / resync payload) ----------

    def snapshot_offer(self) -> Dict[str, Any]:
        """Register a full-state snapshot for chunked pull and return its
        descriptor (id, size, whole-payload sha256). The receiver pulls
        size-bounded chunks via :meth:`snapshot_chunk`, verifies the hash
        over the assembled bytes, and applies atomically through
        ``load_snapshot`` — so a torn transfer can never half-apply."""
        blob = json.dumps(self.backing.snapshot_state()).encode()
        tid = uuid.uuid4().hex
        with self._transfer_lock:
            self._transfers[tid] = blob
            while len(self._transfers) > 4:  # bounded outbox, FIFO evict
                self._transfers.pop(next(iter(self._transfers)))
        return {
            "id": tid,
            "size": len(blob),
            "sha256": hashlib.sha256(blob).hexdigest(),
        }

    def snapshot_chunk(self, transfer_id: str, offset: int
                       ) -> Dict[str, Any]:
        """One size-bounded chunk of a registered transfer. Offsets are
        caller-chosen, so a puller that lost its connection mid-transfer
        RESUMES at the byte it stopped at — re-requesting the same offset
        is idempotent."""
        with self._state_lock:
            if self.crashed:
                raise PeerUnreachable(f"{self.node_id} is down")
        with self._transfer_lock:
            blob = self._transfers.get(transfer_id)
        if blob is None:
            raise UnknownTransfer(
                f"snapshot transfer {transfer_id} is gone (sender "
                f"restarted or outbox evicted it); restart from a fresh "
                f"offer"
            )
        offset = max(0, int(offset))
        data = blob[offset:offset + self.snapshot_chunk_bytes]
        return {
            "data": base64.b64encode(data).decode(),
            "eof": offset + len(data) >= len(blob),
        }

    def snapshot_done(self, transfer_id: str) -> Dict[str, Any]:
        with self._transfer_lock:
            self._transfers.pop(transfer_id, None)
        return {"ok": True}

    def _pull_snapshot(self, peer: str, offer: Dict[str, Any]
                       ) -> Dict[str, Any]:
        """Pull an offered snapshot from ``peer`` in bounded chunks.
        A dropped connection surfaces as PeerUnreachable for ONE chunk;
        the bounded retry re-requests the SAME offset, so the transfer
        resumes where it stopped instead of starting over. The assembled
        bytes must match the offer's sha256 before they are decoded —
        a truncated or spliced transfer is rejected, never applied."""
        size = int(offer["size"])
        buf = bytearray()
        chunks = 0
        with trace.start_span(
            "replica.snapshot",
            attrs={"node": self.node_id, "from": peer, "bytes": size},
        ) as sp:
            while len(buf) < size:
                last: Optional[Exception] = None
                for attempt in range(5):
                    try:
                        res = self.hub.call(
                            self.node_id, peer, "snapshot_chunk",
                            offer["id"], len(buf),
                        )
                        break
                    except PeerUnreachable as e:
                        # resume path: same offset, jittered wait (the
                        # severed connection is the common chaos fault)
                        last = e
                        time.sleep(0.02 * (attempt + 1))  # bounded, linear
                else:
                    raise last if last is not None else PeerUnreachable(
                        f"snapshot pull from {peer} stalled"
                    )
                data = base64.b64decode(res["data"])
                if not data and not res.get("eof"):
                    raise PeerUnreachable(
                        f"snapshot pull from {peer} made no progress at "
                        f"offset {len(buf)}/{size}"
                    )
                buf += data
                chunks += 1
                metrics.replication_snapshot_bytes.inc(len(data))
                if res.get("eof"):
                    break
            if hashlib.sha256(bytes(buf)).hexdigest() != offer["sha256"]:
                raise UnknownTransfer(
                    f"snapshot transfer {offer['id']} content hash "
                    f"mismatch after {len(buf)} bytes; restart from a "
                    f"fresh offer"
                )
            sp.set_attr("chunks", chunks)
        try:
            self.hub.call(self.node_id, peer, "snapshot_done", offer["id"])
        except (PeerUnreachable, UnknownTransfer):
            pass  # best-effort cleanup; the bounded outbox evicts anyway
        return json.loads(bytes(buf))

    # -- RPC handlers (invoked through the hub) ------------------------------

    def request_vote(self, epoch: int, candidate_id: str,
                     prevote: bool = False) -> Dict[str, Any]:
        """``prevote=True`` answers "WOULD you grant?" with zero durable
        or volatile state change — the Raft pre-vote probe that keeps a
        doomed campaign from leaving a leader-fencing epoch behind."""
        with self._state_lock:
            if self.crashed:
                raise PeerUnreachable(f"{self.node_id} is down")
            rv = self.backing.current_rv()
            if epoch <= self.epoch:
                return {"granted": False, "rv": rv, "epoch": self.epoch}
            now = _monotonic()
            if self.role == LEADER and now < self._lease_deadline:
                # a live leader does not vote itself out under a flaky
                # candidate (rule 2)
                return {"granted": False, "rv": rv, "epoch": self.epoch}
            if (
                self.role == FOLLOWER
                and self.leader_id is not None
                and self.leader_id != candidate_id
                and now < self._lease_until
            ):
                return {"granted": False, "rv": rv, "epoch": self.epoch}
            if prevote:
                return {"granted": True, "rv": rv, "epoch": self.epoch}
            self._adopt_epoch(epoch)  # THE vote: durable, one per epoch
            self.role = FOLLOWER
            self.leader_id = None
            return {"granted": True, "rv": rv, "epoch": epoch}

    def append_entries(self, epoch: int, leader_id: str, prev_rv: int,
                       prev_hash: Optional[str],
                       entries: List[Dict[str, Any]]) -> Dict[str, Any]:
        with self._apply_lock:
            return self._append_entries_locked(epoch, leader_id, prev_rv,
                                               prev_hash, entries)

    def _append_entries_locked(self, epoch: int, leader_id: str,
                               prev_rv: int, prev_hash: Optional[str],
                               entries: List[Dict[str, Any]]
                               ) -> Dict[str, Any]:
        with self._state_lock:
            if self.crashed:
                raise PeerUnreachable(f"{self.node_id} is down")
            if epoch < self.epoch:
                raise StaleEpoch(self.epoch)
            if epoch > self.epoch:
                self._adopt_epoch(epoch)
            if self.role == LEADER and leader_id != self.node_id:
                # same-epoch second leader is impossible (votes are
                # durable + majorities intersect); this branch is a
                # higher-epoch leader superseding us
                self.role = FOLLOWER
            self.leader_id = leader_id
            self._lease_until = _monotonic() + self.lease_duration
        my_rv = self.backing.current_rv()
        if my_rv < prev_rv:
            return {"behind": my_rv}
        if my_rv > prev_rv:
            if entries and my_rv >= entries[-1]["rv"] and (
                self.backing.tail_hash(entries[-1]["rv"])
                == entry_hash(entries[-1])
            ):
                return {"applied": my_rv}  # duplicate ship: already have it
            return {"divergent": True}
        if prev_rv > 0 and prev_hash is not None:
            mine = self.backing.tail_hash(prev_rv)
            if mine is not None and mine != prev_hash:
                return {"divergent": True}  # dead-epoch suffix at my tail
        if entries:
            self.backing.apply_replicated(entries)
            # remember the delivering ship's span (the wire route's
            # server-side span, or — in-process — the leader's ship span
            # itself, since hub dispatch is synchronous on its thread):
            # a later election anchors on it for trace continuity
            ctx = trace.current_ids()
            if ctx is not None:
                self._last_ship_ctx = ctx
        return {"applied": self.backing.current_rv()}

    def fetch_entries(self, after_rv: int,
                      after_hash: Optional[str]) -> Dict[str, Any]:
        """Tail (or snapshot OFFER — the payload itself moves as bounded
        chunks, never one giant response) for a catching-up candidate."""
        with self._state_lock:
            if self.crashed:
                raise PeerUnreachable(f"{self.node_id} is down")
        if after_rv > 0 and after_hash is not None:
            mine = self.backing.tail_hash(after_rv)
            if mine is not None and mine != after_hash:
                return {"snapshot_offer": self.snapshot_offer()}
        try:
            return {"entries": self.backing.log_tail(after_rv)}
        except LogTruncated:
            return {"snapshot_offer": self.snapshot_offer()}

    def install_snapshot(self, epoch: int, leader_id: str,
                         snap: Dict[str, Any]) -> Dict[str, Any]:
        """Full-state resync. ``snap`` is either an inline snapshot dict
        (direct-call harnesses) or ``{"offer": ...}`` — the normal path:
        this node PULLS the payload from ``leader_id`` in bounded,
        hash-verified, resumable chunks, then applies it atomically via
        ``load_snapshot``. A failed pull returns ``{"failed": ...}`` so
        the sender degrades that ship to majority-only (and re-offers on
        its next heartbeat) instead of erroring the write it was
        shipping."""
        with self._apply_lock:
            with self._state_lock:
                if self.crashed:
                    raise PeerUnreachable(f"{self.node_id} is down")
                if epoch < self.epoch:
                    raise StaleEpoch(self.epoch)
                if epoch > self.epoch:
                    self._adopt_epoch(epoch)
                self.role = FOLLOWER
                self.leader_id = leader_id
                self._lease_until = _monotonic() + self.lease_duration
            if isinstance(snap, dict) and "offer" in snap:
                try:
                    snap = self._pull_snapshot(leader_id, snap["offer"])
                except (PeerUnreachable, UnknownTransfer, ValueError,
                        KeyError) as e:
                    log.warning("%s: snapshot pull from %s failed: %s",
                                self.node_id, leader_id, e)
                    return {"failed": f"{type(e).__name__}: {e}"}
                with self._state_lock:
                    # the pull took wall time: a newer reign may have
                    # superseded the sender mid-transfer, and applying
                    # the dead reign's snapshot now could truncate acked
                    # writes the new reign already shipped us
                    if epoch < self.epoch:
                        raise StaleEpoch(self.epoch)
            return {"applied": self.backing.load_snapshot(snap)}

    def replica_status(self) -> Dict[str, Any]:
        """The `ctl store status` / /v1/replica/status payload."""
        with self._state_lock:
            now = _monotonic()
            lease = (self._lease_deadline if self.role == LEADER
                     else self._lease_until) - now
            out = {
                "node": self.node_id,
                "role": self.role if not self.crashed else "down",
                "epoch": self.epoch,
                "applied_rv": (0 if self.crashed
                               else self.backing.current_rv()),
                "lease_remaining_s": round(max(0.0, lease), 3),
                "leader": self._leader_hint(),
                # full-membership hint: `ctl store status` resolves the
                # whole set from ANY one endpoint by following these
                # (node id → advertised URL; non-URL entries are
                # in-process sets, which the client skips)
                "peers": dict(self.rset.advertise),
            }
            if self.role == LEADER and not self.crashed:
                head = self.backing.current_rv()
                out["lag_entries"] = {
                    p: max(0, head - rv) for p, rv in self._peer_rv.items()
                }
        return out

    # -- duck-typed store surface --------------------------------------------

    def create(self, obj: Any) -> Any:
        return self._leader_write(lambda: self.backing.create(obj))

    def update(self, obj: Any, force: bool = False) -> Any:
        return self._leader_write(lambda: self.backing.update(obj, force))

    def patch(self, kind: str, namespace: str, name: str, patch: Any, *,
              subresource: Optional[str] = None) -> Any:
        return self._leader_write(
            lambda: self.backing.patch(kind, namespace, name, patch,
                                       subresource=subresource)
        )

    def patch_batch(self, items: List[Dict[str, Any]]) -> List[Any]:
        """Per-item semantics come from the backing loop; the whole
        batch's new log rows ship as one tail (per-item errors commit
        nothing and ship nothing, exactly like the single verbs)."""
        return self._leader_write(lambda: self.backing.patch_batch(items))

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        return self._leader_write(
            lambda: self.backing.delete(kind, namespace, name)
        )

    def try_delete(self, kind: str, namespace: str, name: str
                   ) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except KeyError:  # NotFound subclasses KeyError
            return None

    def get(self, kind: str, namespace: str, name: str) -> Any:
        return self.backing.get(kind, namespace, name)

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        return self.backing.try_get(kind, namespace, name)

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[Any]:
        return self.backing.list(kind, namespace, selector)

    def current_rv(self) -> int:
        return self.backing.current_rv()

    def watch(self, kind: Optional[str] = None):
        return self.backing.watch(kind)

    def stop_watch(self, q) -> None:
        self.backing.stop_watch(q)

    def add_relist_listener(self, cb) -> None:
        self.backing.add_relist_listener(cb)

    # -- lifecycle -----------------------------------------------------------

    def crash(self) -> None:
        """SIGKILL semantics: drop the node without any clean shutdown —
        the WAL is left unCheckpointed on disk, exactly what a killed
        process strands. The sqlite connection is ABANDONED, deliberately
        not closed: sqlite3.Connection.close() racing another thread's
        in-flight execute is a C-level crash (a real segfault, observed
        under the auto-renew ticker), and a real SIGKILL runs no close()
        either. A verb already past the crash check simply finishes its
        local commit and then fails the majority ship (the hub is down
        for this node) — the honest INDETERMINATE outcome. The handle
        stays referenced on the dead backing so no GC close ever runs;
        it leaks until process exit, which is the price of fidelity."""
        with self._state_lock:
            self.crashed = True
            self.role = FOLLOWER
        self.hub.set_down(self.node_id, True)
        self.backing._stop.set()
        self._abandoned = self.backing

    def reopen(self) -> None:
        """Restart after a crash: recover the sqlite file (WAL replay),
        reload the durable epoch, rejoin as a follower."""
        self.backing = SqliteStore(self.path,
                                   poll_interval=self.poll_interval)
        with self._state_lock:
            self.epoch = int(self.backing.get_meta("epoch", "0"))
            self.role = FOLLOWER
            self.leader_id = None
            self.crashed = False
            self._lease_until = 0.0
        self._shipped_rv = self.backing.current_rv()
        self._peer_down_until = {}
        self._resync_pending = set()
        self._resync_active = set()
        with self._transfer_lock:
            self._transfers = {}
        self._last_ship_ctx = None
        self._reign_ctx = None
        self.hub.set_down(self.node_id, False)

    def close(self) -> None:
        if not self.crashed:
            self.backing.close()


class ReplicaSet:
    """Assembles N :class:`ReplicaNode`\\ s over one :class:`PeerHub`.

    Two drive modes:

    - **manual** (default; the analysis harnesses): no background
      threads; call :meth:`elect` to install a leader. The lease is long,
      so leadership is stable until explicitly taken over or fenced.
    - **auto** (``start()``; the chaos e2e): a seeded per-node ticker
      renews the leader's lease and campaigns on expiry with node-skewed
      jitter, so failover happens on its own within ~2 lease durations
      and the first winner is deterministic for a seed.
    """

    def __init__(self, n: int = 3, *, dir: str, lease_duration: float = 30.0,
                 retry_period: float = 0.1, poll_interval: float = 0.05,
                 seed: int = 0):
        self.hub = PeerHub()
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.node_ids = [f"n{i}" for i in range(n)]
        self.advertise: Dict[str, str] = {}
        self.leadership_log: List[Tuple[int, str]] = []
        self._log_lock = threading.Lock()
        self._seed = seed
        self._stop = threading.Event()
        self._tickers: List[threading.Thread] = []
        self.nodes: Dict[str, ReplicaNode] = {}
        for nid in self.node_ids:
            node = ReplicaNode(
                nid, os.path.join(dir, f"{nid}.db"), self.hub, self,
                lease_duration=lease_duration, poll_interval=poll_interval,
            )
            self.nodes[nid] = node
            self.hub.register(node)

    # -- bookkeeping ---------------------------------------------------------

    def _record_leader(self, epoch: int, node_id: str) -> None:
        with self._log_lock:
            self.leadership_log.append((epoch, node_id))

    def set_advertise(self, mapping: Dict[str, str]) -> None:
        """node id → advertised URL, once the HTTP servers know their
        ports; NotLeader hints then carry an address a client can dial."""
        self.advertise.update(mapping)

    # -- election ------------------------------------------------------------

    def elect(self, node_id: str) -> bool:
        """Manual, synchronous lease takeover by ``node_id``."""
        return self.nodes[node_id].campaign()

    def expire_leases(self) -> None:
        """Zero every live node's follower lease — the operator's forced-
        failover hand (≙ deleting the kube Lease object), and the manual-
        mode harnesses' fast-forward past the lease wait that auto mode
        serves out in real time. Votes stay epoch-gated, so safety (one
        leader per epoch) is untouched; only the liveness delay is
        skipped."""
        for node in self.nodes.values():
            with node._state_lock:
                node._lease_until = 0.0

    def leader(self) -> Optional[ReplicaNode]:
        best = None
        for node in self.nodes.values():
            with node._state_lock:
                if node.role == LEADER and not node.crashed:
                    if best is None or node.epoch > best.epoch:
                        best = node
        return best

    def wait_for_leader(self, timeout: float = 10.0
                        ) -> Optional[ReplicaNode]:
        deadline = _monotonic() + timeout
        while _monotonic() < deadline:
            node = self.leader()
            if node is not None:
                return node
            if self._stop.wait(0.02):
                return None
        return None

    def quiesce(self, timeout: float = 10.0) -> bool:
        """Wait until every live node has applied the leader's head rv
        (a leader heartbeat drags laggards); the deterministic read
        barrier harnesses use before diffing follower state."""
        deadline = _monotonic() + timeout
        while _monotonic() < deadline:
            lead = self.leader()
            if lead is not None:
                lead.renew()
                head = lead.backing.current_rv()
                live = [n for n in self.nodes.values() if not n.crashed]
                if all(n.backing.current_rv() == head for n in live):
                    return True
            if self._stop.wait(0.02):
                return False
        return False

    # -- auto mode -----------------------------------------------------------

    def start(self) -> "ReplicaSet":
        for i, nid in enumerate(self.node_ids):
            t = threading.Thread(
                target=self._tick_loop,
                args=(self.nodes[nid],
                      random.Random(f"{self._seed}:{nid}"), i),
                name=f"replica-tick-{nid}", daemon=True,
            )
            self._tickers.append(t)
            t.start()
        return self

    def _tick_loop(self, node: ReplicaNode, rng: random.Random,
                   index: int) -> None:
        while not self._stop.wait(self.retry_period):
            try:
                tick_node(node, rng, index, self.retry_period, self._stop)
            except Exception:
                # a ticker must survive transient errors (a peer crashing
                # mid-RPC); a dead ticker would silently end failover
                log.debug("replica ticker error", exc_info=True)

    # -- fault surface -------------------------------------------------------

    def crash(self, node_id: str) -> None:
        self.nodes[node_id].crash()

    def restart(self, node_id: str) -> None:
        self.nodes[node_id].reopen()

    # -- status / lifecycle --------------------------------------------------

    def status(self) -> List[Dict[str, Any]]:
        return [self.nodes[nid].replica_status() for nid in self.node_ids]

    def client(self, read_from: Optional[str] = None) -> "ReplicaClient":
        return ReplicaClient(self, read_from=read_from)

    def stop(self) -> None:
        self._stop.set()
        for t in self._tickers:
            t.join(timeout=2.0)
        for node in self.nodes.values():
            node.close()


class NodeTarget:
    """ChaosController process-target adapter for an in-process replica
    node: ``kill`` is the SIGKILL-equivalent hard crash, ``restart``
    reopens from the same files. ``node_id=None`` resolves 'the current
    leader' at fire time — the scripted leader-kill."""

    def __init__(self, rset: ReplicaSet, node_id: Optional[str] = None):
        self.rset = rset
        self.node_id = node_id
        self.killed: Optional[str] = None

    def _resolve(self) -> str:
        if self.node_id is not None:
            return self.node_id
        lead = self.rset.leader()
        if lead is None:
            raise RuntimeError("no leader to target")
        return lead.node_id

    def kill(self) -> None:
        self.killed = self._resolve()
        self.rset.crash(self.killed)

    def term(self) -> None:
        self.kill()  # a store node has no graceful-drain distinction

    def restart(self) -> None:
        target = self.killed or self._resolve()
        self.rset.restart(target)


class ReplicaClient:
    """The in-process failover client: same duck-typed store surface,
    mutations routed to the leased leader (following NotLeader hints with
    bounded jittered backoff — the socketless twin of HttpStoreClient's
    multi-endpoint rotation), reads and watch fan-out served by a
    follower, which is exactly the replica set's read contract: lag is
    legal, rv regression is not."""

    def __init__(self, rset: ReplicaSet, *, read_from: Optional[str] = None,
                 mutation_attempts: int = 12, backoff: float = 0.05):
        self._set = rset
        self._read_from = read_from
        self._attempts = mutation_attempts
        self._backoff = backoff
        self._rng = random.Random(f"client:{rset._seed}")
        self._guess: Optional[ReplicaNode] = None
        # per-queue owner node: stop_watch must unregister a queue from
        # the node that issued it, not whichever node served the LATEST
        # watch() call (a silently un-stopped queue fills forever)
        self._watch_nodes: Dict[int, ReplicaNode] = {}
        self._stop = threading.Event()

    # -- routing -------------------------------------------------------------

    def _read_node(self) -> ReplicaNode:
        if self._read_from is not None:
            node = self._set.nodes[self._read_from]
            if not node.crashed:
                return node
        # failover reads pick the MOST CAUGHT-UP live node (leader
        # included), not merely the first live follower: falling back
        # from a crashed pinned node to a lagging follower could
        # un-observe an acked write this client already read — the rv
        # regression the follower-read contract forbids (per-node reads
        # stay monotone; cross-node failover must not go backwards
        # through the acked history)
        live = [n for n in self._set.nodes.values() if not n.crashed]
        if not live:
            raise PeerUnreachable("no live replica to read from")
        # ties (the healthy steady state) still prefer a follower —
        # spreading reads off the leader is the replica set's point
        return max(live, key=lambda n: (n.backing.current_rv(),
                                        n.role != LEADER))

    def _mutate(self, fn: Callable[[ReplicaNode], Any]) -> Any:
        """Route a mutation to the leader, re-resolving on NotLeader /
        unreachable with bounded jittered backoff. Only DEFINITE
        failures are retried; ReplicationUnavailable (indeterminate)
        propagates — the caller owns the re-read."""
        delay = self._backoff
        last: Optional[Exception] = None
        for _ in range(self._attempts):
            node = self._guess
            if node is None or node.crashed:
                node = self._set.leader()
            if node is not None and not node.crashed:
                try:
                    out = fn(node)
                    self._guess = node
                    return out
                except NotLeader as e:
                    last = e
                    hint = e.leader
                    self._guess = next(
                        (n for n in self._set.nodes.values()
                         if n.node_id == hint and not n.crashed),
                        None,
                    )
                except PeerUnreachable as e:
                    last = e
                    self._guess = None
            jittered = delay * (1 + self._rng.uniform(0, 0.25))
            if self._stop.wait(jittered):
                break
            delay = min(delay * 2, 1.0)
        raise last if last is not None else PeerUnreachable(
            "no replica leader reachable"
        )

    def replica_status(self) -> List[Dict[str, Any]]:
        return self._set.status()

    # -- duck-typed store surface --------------------------------------------

    def create(self, obj: Any) -> Any:
        return self._mutate(lambda n: n.create(obj))

    def update(self, obj: Any, force: bool = False) -> Any:
        return self._mutate(lambda n: n.update(obj, force))

    def patch(self, kind: str, namespace: str, name: str, patch: Any, *,
              subresource: Optional[str] = None) -> Any:
        return self._mutate(
            lambda n: n.patch(kind, namespace, name, patch,
                              subresource=subresource)
        )

    def patch_batch(self, items: List[Dict[str, Any]]) -> List[Any]:
        return self._mutate(lambda n: n.patch_batch(items))

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        return self._mutate(lambda n: n.delete(kind, namespace, name))

    def try_delete(self, kind: str, namespace: str, name: str
                   ) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except KeyError:  # NotFound subclasses KeyError
            return None

    def get(self, kind: str, namespace: str, name: str) -> Any:
        return self._read_node().get(kind, namespace, name)

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        return self._read_node().try_get(kind, namespace, name)

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[Any]:
        return self._read_node().list(kind, namespace, selector)

    def current_rv(self) -> int:
        return self._read_node().current_rv()

    def watch(self, kind: Optional[str] = None):
        node = self._read_node()
        q = node.watch(kind)
        self._watch_nodes[id(q)] = node
        return q

    def stop_watch(self, q) -> None:
        node = self._watch_nodes.pop(id(q), None)
        if node is not None:
            node.stop_watch(q)

    def add_relist_listener(self, cb) -> None:
        self._read_node().add_relist_listener(cb)

    def close(self) -> None:
        self._stop.set()
