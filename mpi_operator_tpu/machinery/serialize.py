"""Kind registry + generic dataclass (de)serialization.

The in-process ObjectStore passes live objects around, so it never needs to
serialize. A shared backend (machinery/sqlite_store.py) does: every stored
kind must round-trip through plain dicts. API types carry hand-written
``from_dict`` (manifest-facing, with aliases); the machinery kinds decode
generically from their dataclass shape here.

≙ the scheme/codec registration the reference generates per API group
(v2/pkg/apis/kubeflow/v2beta1/register.go:52, zz_generated.deepcopy.go) —
one registry instead of 39k generated lines, because the dataclasses are
their own schema.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Type

from mpi_operator_tpu.api.types import Alert, TPUJob, TPUServe
from mpi_operator_tpu.machinery import objects as mo


def _decode_value(tp: Any, v: Any) -> Any:
    """Decode ``v`` into type ``tp`` (a typing annotation)."""
    if v is None:
        return None
    origin = typing.get_origin(tp)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return _decode_value(args[0], v) if args else v
    if origin in (dict, Dict):
        kt, vt = typing.get_args(tp) or (str, Any)
        return {k: _decode_value(vt, x) for k, x in v.items()}
    if origin in (list, typing.List):
        (et,) = typing.get_args(tp) or (Any,)
        return [_decode_value(et, x) for x in v]
    if dataclasses.is_dataclass(tp):
        return decode_dataclass(tp, v)
    return v


def decode_dataclass(cls: Type, d: Dict[str, Any]) -> Any:
    """Build ``cls`` from a dict produced by ``to_dict`` (pruned: missing
    keys take field defaults). Prefers the class's own ``from_dict``."""
    own = cls.__dict__.get("from_dict")
    if own is not None:
        return cls.from_dict(d)
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in d:
            kwargs[f.name] = _decode_value(hints.get(f.name, Any), d[f.name])
    return cls(**kwargs)


KIND_CLASSES: Dict[str, Type] = {
    "TPUJob": TPUJob,
    "TPUServe": TPUServe,
    "Alert": Alert,
    "Pod": mo.Pod,
    "Service": mo.Service,
    "ConfigMap": mo.ConfigMap,
    "PodGroup": mo.PodGroup,
    "Event": mo.Event,
    "Node": mo.Node,
}


def encode(obj: Any) -> Dict[str, Any]:
    return obj.to_dict()


def decode(kind: str, d: Dict[str, Any]) -> Any:
    cls = KIND_CLASSES.get(kind)
    if cls is None:
        raise KeyError(f"unknown kind {kind!r}")
    return decode_dataclass(cls, d)
