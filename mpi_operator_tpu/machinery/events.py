"""Event recorder.

≙ record.EventRecorder wired in NewMPIJobController
(/root/reference/v2/pkg/controller/mpi_job_controller.go:263-268) and used as
the user-facing audit log (Created/Running/Succeeded/Failed, validation
errors truncated to 1024 chars via truncateMessage :1524-1530). Events land in
the ObjectStore so integration tests can assert the emitted sequence the way
the reference's eventChecker does (v2/test/integration/main_test.go:116-178).
"""

from __future__ import annotations

import itertools
import time
import uuid
from typing import Any, List

from mpi_operator_tpu.api.types import ObjectMeta
from mpi_operator_tpu.machinery.objects import Event, ObjectRef
from mpi_operator_tpu.machinery.store import AlreadyExists, ObjectStore

MAX_MESSAGE_LEN = 1024  # ≙ truncateMessage (mpi_job_controller.go:1524-1530)

NORMAL = "Normal"
WARNING = "Warning"


def truncate_message(message: str) -> str:
    if len(message) <= MAX_MESSAGE_LEN:
        return message
    suffix = " [truncated]"
    return message[: MAX_MESSAGE_LEN - len(suffix)] + suffix


class EventRecorder:
    def __init__(self, store: ObjectStore, component: str = "tpujob-controller"):
        self._store = store
        self._component = component
        # per-RECORDER nonce in the event name: the old process-local
        # itertools.count() collided the moment two processes (leader +
        # standby, controller + node monitor) recorded against the same
        # object — both minted "<obj>.N" and the second create failed
        # AlreadyExists, silently dropping audit entries (≙ kube events,
        # which are named with a hashed suffix for exactly this reason)
        self._nonce = uuid.uuid4().hex[:8]
        self._counter = itertools.count()

    def event(self, obj: Any, etype: str, reason: str, message: str) -> Event:
        m = obj.metadata
        for _ in range(3):
            ev = Event(
                metadata=ObjectMeta(
                    name=f"{m.name}.{self._nonce}.{next(self._counter)}",
                    namespace=m.namespace,
                    labels={"component": self._component},
                ),
                involved=ObjectRef(
                    kind=obj.kind, namespace=m.namespace, name=m.name,
                    uid=m.uid,
                ),
                type=etype,
                reason=reason,
                message=truncate_message(message),
                timestamp=time.time(),
            )
            try:
                return self._store.create(ev)
            except AlreadyExists:
                # astronomically unlikely (a nonce collision with another
                # recorder at the same count); the counter advanced, so
                # the retry mints a fresh name instead of dropping the
                # audit entry
                continue
        raise AlreadyExists(
            f"event name collision persisted for {m.name!r} "
            f"(recorder nonce {self._nonce})"
        )

    # -- test helpers (≙ eventChecker) --------------------------------------

    def events_for(self, obj: Any) -> List[Event]:
        evs = [
            e
            for e in self._store.list("Event", obj.metadata.namespace)
            if e.involved.name == obj.metadata.name and e.involved.kind == obj.kind
        ]
        evs.sort(key=lambda e: e.timestamp)
        return evs

    def reasons_for(self, obj: Any) -> List[str]:
        return [e.reason for e in self.events_for(obj)]
