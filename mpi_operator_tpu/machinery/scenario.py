"""Declarative fleet scenarios: a day in the life of the cluster,
compressed into minutes and replayable bit-for-bit.

Every bench mode so far torments ONE subsystem at a time; production is
all of them at once, for hours. This module extends the ChaosScript
timeline grammar (machinery/chaos.py) from a fault catalog into a full
WORKLOAD DSL, so `BENCH_CP_MODES=soak` can run a scripted "day" against
the deployed shape — diurnal serving load, seeded batch arrivals with a
tenant mix, a rolling maintenance wave, and scripted faults (including
the zero-warning `reclaim`) — with the SLO plane as the only judge:

- :class:`VirtualClock` — ``scale`` scenario seconds pass per wall
  second. Every schedule in the DSL is written in SCENARIO time; the
  clock converts at the edges (timer-wheel delays, notice deadlines), so
  a six-hour day compresses into a minutes-long run whose event ORDER
  and CONTENT are invariant under the compression factor.
- :class:`Scenario` — the parsed, validated document. Like ChaosScript,
  parsing fails fast on unknown sections, unknown knobs, or nonsense
  values: a typo'd curve silently doing nothing would make a "passing"
  soak meaningless. All randomness (arrival times, job names,
  maintenance victims) is resolved by :meth:`Scenario.events` from the
  document seed — two calls return the identical timeline, which is the
  determinism anchor the soak bench asserts by running twice on one
  seed.
- :class:`ScenarioEngine` — walks the precomputed timeline on a thread:
  serve QPS set-points drive the hollow fleet's :class:`ServeLoadModel`,
  arrivals create real TPUJobs through the validating client, waves arm
  the fleet's :meth:`arm_maintenance` (whose knobs the threaded clock
  reads as scenario time), and the embedded chaos section rides an
  ordinary :class:`ChaosController` with wall-converted fire times.
  Like the chaos controller, ``executed`` is an audit trail — a soak
  leaves a replayable record, not a vibe.

Scenario format (YAML or JSON; ALL times/rates are scenario seconds)::

    seed: 1807
    scale: 60.0          # one wall second = one scenario minute
    duration: 21600      # a six-hour day
    serves:
      - {serve: soak/web, curve: diurnal, peak_qps: 400, trough_qps: 40,
         period: 21600, interval: 300}
    arrivals:
      - {tenant: etl, rate_per_hour: 40, pods: 2, chips: 1, end: 18000}
    maintenance:
      - {at: 7200, fraction: 0.2, notice: 600, stagger: 120}
    chaos:
      - {at: 10800, fault: reclaim, target: hollow-0003}
"""

from __future__ import annotations

import logging
import math
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from mpi_operator_tpu.machinery.chaos import (
    ChaosAction,
    ChaosController,
    ChaosScript,
    ChaosScriptError,
)

log = logging.getLogger("tpujob.scenario")

# the tenant-mix label arrivals stamp on their jobs (fairness dashboards
# and the soak's per-tenant assertions read it back)
LABEL_TENANT = "tpujob.dev/tenant"

CURVES = ("diurnal", "flat")


class ScenarioError(ValueError):
    """Malformed scenario document (the ChaosScript fail-fast posture)."""


class VirtualClock:
    """Scenario time ↔ wall time. ``scale`` is scenario seconds per wall
    second (scale 60: a scripted hour takes a wall minute). Conversions
    are stateless — only :meth:`now` anchors to construction time — so
    one clock can be shared by the engine, the hollow fleet's timer
    wheel, and the bench without ordering constraints."""

    def __init__(self, scale: float = 1.0):
        scale = float(scale)
        if not scale > 0:
            raise ValueError(f"time scale must be > 0, got {scale}")
        self.scale = scale
        self._t0 = time.monotonic()

    def to_wall(self, virtual_s: float) -> float:
        return float(virtual_s) / self.scale

    def to_virtual(self, wall_s: float) -> float:
        return float(wall_s) * self.scale

    def now(self) -> float:
        """Scenario seconds elapsed since this clock was created."""
        return (time.monotonic() - self._t0) * self.scale


def _reject_unknown(section: str, i: int, doc: Dict[str, Any],
                    allowed: set) -> None:
    unknown = set(doc) - allowed
    if unknown:
        raise ScenarioError(
            f"{section}[{i}]: unknown keys {sorted(unknown)} (they would "
            f"be silently ignored; valid: {sorted(allowed)})"
        )


def _num(section: str, i: int, doc: Dict[str, Any], key: str,
         default: Optional[float] = None, *, minimum: float = 0.0) -> float:
    if key not in doc:
        if default is None:
            raise ScenarioError(f"{section}[{i}]: {key!r} is required")
        return default
    try:
        v = float(doc[key])
    except (TypeError, ValueError):
        raise ScenarioError(
            f"{section}[{i}]: {key!r} must be a number, got {doc[key]!r}"
        ) from None
    if v < minimum:
        raise ScenarioError(f"{section}[{i}]: {key!r} must be >= {minimum}")
    return v


@dataclass(frozen=True)
class ServeCurve:
    """One serve's offered-QPS schedule. ``diurnal`` is the classic
    day-shape: trough at t=0, peak half a ``period`` later (a raised
    cosine); ``flat`` pins ``peak_qps``. The engine samples the curve
    every ``interval`` scenario seconds into set-point events."""

    serve: str              # "<ns>/<name>" — the ServeLoadModel key
    curve: str = "diurnal"
    peak_qps: float = 100.0
    trough_qps: float = 0.0
    period: float = 86400.0
    interval: float = 60.0
    start: float = 0.0
    end: Optional[float] = None

    def qps_at(self, t: float) -> float:
        if self.curve == "flat":
            return self.peak_qps
        phase = 2.0 * math.pi * ((t - self.start) / self.period)
        mid = (self.peak_qps + self.trough_qps) / 2.0
        amp = (self.peak_qps - self.trough_qps) / 2.0
        return mid - amp * math.cos(phase)


@dataclass(frozen=True)
class ArrivalProcess:
    """A seeded Poisson arrival stream of batch gangs for one tenant:
    exponential interarrivals at ``rate_per_hour`` between ``start`` and
    ``end`` (scenario seconds), each submitting a ``pods``-member gang of
    ``chips`` chips per host."""

    tenant: str
    rate_per_hour: float
    pods: int = 1
    chips: int = 1
    start: float = 0.0
    end: Optional[float] = None


@dataclass(frozen=True)
class MaintenanceWave:
    """A rolling maintenance wave armed at ``at``: ``fraction`` of the
    fleet (seeded choice) gets a notice with ``notice`` scenario seconds
    of warning, one node every ``stagger``."""

    at: float
    fraction: float = 0.1
    notice: float = 600.0
    stagger: float = 60.0


class Scenario:
    """A validated scenario document. Parse once; :meth:`events` resolves
    every seeded draw into one deterministic, sorted timeline."""

    def __init__(self, *, seed: int, scale: float, duration: float,
                 serves: List[ServeCurve],
                 arrivals: List[ArrivalProcess],
                 maintenance: List[MaintenanceWave],
                 chaos: Optional[ChaosScript]):
        self.seed = seed
        self.scale = scale
        self.duration = duration
        self.serves = serves
        self.arrivals = arrivals
        self.maintenance = maintenance
        self.chaos = chaos

    @classmethod
    def parse(cls, doc: Dict[str, Any]) -> "Scenario":
        if not isinstance(doc, dict):
            raise ScenarioError("scenario must be a mapping")
        unknown = set(doc) - {"seed", "scale", "duration", "serves",
                              "arrivals", "maintenance", "chaos"}
        if unknown:
            raise ScenarioError(f"unknown top-level keys {sorted(unknown)}")
        seed = doc.get("seed", 0)
        if not isinstance(seed, int):
            raise ScenarioError(f"seed must be an integer, got {seed!r}")
        top = {"scale": doc.get("scale", 1.0),
               "duration": doc.get("duration")}
        scale = _num("scenario", 0, top, "scale", 1.0)
        if scale <= 0:
            raise ScenarioError("scale must be > 0")
        duration = _num("scenario", 0, top, "duration")
        if duration <= 0:
            raise ScenarioError("duration must be > 0")

        serves: List[ServeCurve] = []
        for i, s in enumerate(doc.get("serves") or []):
            if not isinstance(s, dict):
                raise ScenarioError(f"serves[{i}]: must be a mapping")
            _reject_unknown("serves", i, s, {
                "serve", "curve", "peak_qps", "trough_qps", "period",
                "interval", "start", "end",
            })
            serve = str(s.get("serve", ""))
            if "/" not in serve:
                raise ScenarioError(
                    f"serves[{i}]: 'serve' must be '<namespace>/<name>', "
                    f"got {serve!r}"
                )
            curve = str(s.get("curve", "diurnal"))
            if curve not in CURVES:
                raise ScenarioError(
                    f"serves[{i}]: unknown curve {curve!r} (one of {CURVES})"
                )
            serves.append(ServeCurve(
                serve=serve, curve=curve,
                peak_qps=_num("serves", i, s, "peak_qps", 100.0),
                trough_qps=_num("serves", i, s, "trough_qps", 0.0),
                period=_num("serves", i, s, "period", duration,
                            minimum=1e-9),
                interval=_num("serves", i, s, "interval", 60.0,
                              minimum=1e-9),
                start=_num("serves", i, s, "start", 0.0),
                end=(_num("serves", i, s, "end") if "end" in s else None),
            ))

        arrivals: List[ArrivalProcess] = []
        for i, a in enumerate(doc.get("arrivals") or []):
            if not isinstance(a, dict):
                raise ScenarioError(f"arrivals[{i}]: must be a mapping")
            _reject_unknown("arrivals", i, a, {
                "tenant", "rate_per_hour", "pods", "chips", "start", "end",
            })
            tenant = str(a.get("tenant", ""))
            if not tenant:
                raise ScenarioError(f"arrivals[{i}]: 'tenant' is required")
            rate = _num("arrivals", i, a, "rate_per_hour")
            if rate <= 0:
                raise ScenarioError(
                    f"arrivals[{i}]: rate_per_hour must be > 0"
                )
            pods = int(a.get("pods", 1))
            chips = int(a.get("chips", 1))
            if pods < 1 or chips < 1:
                raise ScenarioError(
                    f"arrivals[{i}]: pods and chips must be >= 1"
                )
            arrivals.append(ArrivalProcess(
                tenant=tenant, rate_per_hour=rate, pods=pods, chips=chips,
                start=_num("arrivals", i, a, "start", 0.0),
                end=(_num("arrivals", i, a, "end") if "end" in a else None),
            ))

        waves: List[MaintenanceWave] = []
        for i, w in enumerate(doc.get("maintenance") or []):
            if not isinstance(w, dict):
                raise ScenarioError(f"maintenance[{i}]: must be a mapping")
            _reject_unknown("maintenance", i, w,
                            {"at", "fraction", "notice", "stagger"})
            fraction = _num("maintenance", i, w, "fraction", 0.1)
            if not 0.0 < fraction <= 1.0:
                raise ScenarioError(
                    f"maintenance[{i}]: fraction must be in (0, 1]"
                )
            waves.append(MaintenanceWave(
                at=_num("maintenance", i, w, "at"),
                fraction=fraction,
                notice=_num("maintenance", i, w, "notice", 600.0,
                            minimum=1e-9),
                stagger=_num("maintenance", i, w, "stagger", 60.0),
            ))

        chaos = None
        if doc.get("chaos"):
            # the embedded fault timeline reuses the ChaosScript grammar
            # VERBATIM (knob whitelists included): one validator, one
            # error taxonomy, and the new `reclaim` verb comes for free
            try:
                chaos = ChaosScript.parse(
                    {"seed": seed, "actions": doc["chaos"]}
                )
            except ChaosScriptError as e:
                raise ScenarioError(f"chaos: {e}") from None
        return cls(seed=seed, scale=scale, duration=duration,
                   serves=serves, arrivals=arrivals, maintenance=waves,
                   chaos=chaos)

    @classmethod
    def load(cls, path: str) -> "Scenario":
        import yaml  # YAML is a superset of JSON: one loader serves both

        with open(path) as f:
            try:
                doc = yaml.safe_load(f)
            except yaml.YAMLError as e:
                raise ScenarioError(f"{path}: {e}") from None
        try:
            return cls.parse(doc)
        except ScenarioError as e:
            raise ScenarioError(f"{path}: {e}") from None

    # -- the deterministic timeline -----------------------------------------

    def events(self) -> List[Tuple[float, str, Dict[str, Any]]]:
        """The full resolved timeline: sorted (scenario_t, kind, payload)
        tuples with every random draw already taken from the document
        seed. Chaos actions are NOT in this list — they ride their own
        :class:`ChaosController` (see :meth:`ScenarioEngine.start`) so
        the fault catalog's apply logic is reused, not reimplemented."""
        out: List[Tuple[float, str, Dict[str, Any]]] = []
        for c in self.serves:
            end = min(self.duration, self.end_or(c.end))
            t = c.start
            while t < end:
                out.append((t, "serve-qps", {
                    "serve": c.serve, "qps": round(c.qps_at(t), 3),
                }))
                t += c.interval
        for a in self.arrivals:
            rng = random.Random(f"{self.seed}:arrivals:{a.tenant}")
            end = min(self.duration, self.end_or(a.end))
            t, i = a.start, 0
            while True:
                t += rng.expovariate(a.rate_per_hour / 3600.0)
                if t >= end:
                    break
                out.append((t, "submit", {
                    "name": f"{a.tenant}-{i:04d}", "tenant": a.tenant,
                    "pods": a.pods, "chips": a.chips,
                }))
                i += 1
        for w in self.maintenance:
            out.append((w.at, "maintenance-wave", {
                "fraction": w.fraction, "notice": w.notice,
                "stagger": w.stagger,
            }))
        # stable order under ties: kind then payload repr — the same
        # document always replays the same sequence
        out.sort(key=lambda e: (e[0], e[1], repr(e[2])))
        return out

    def end_or(self, end: Optional[float]) -> float:
        return self.duration if end is None else end


class ScenarioEngine:
    """Drives one :class:`Scenario` against a store (and optionally a
    hollow fleet) in wall time, through a shared :class:`VirtualClock`.

    ``fleet`` is any :class:`~mpi_operator_tpu.executor.hollow.
    HollowFleet`-shaped object; serve curves need its timeline to carry a
    :class:`ServeLoadModel`, maintenance waves ride its
    ``arm_maintenance``. Chaos process/store targets default to the
    fleet's nodes (killable via ``kill_node``) and can be extended or
    overridden with ``chaos_targets``. Missing plumbing fails loudly at
    fire time and lands in ``executed`` — the ChaosController posture: a
    scenario that silently skipped half its script would make a passing
    soak meaningless."""

    def __init__(self, scenario: Scenario, store, *,
                 fleet=None, namespace: str = "soak",
                 clock: Optional[VirtualClock] = None,
                 chaos_proxy=None, chaos_targets: Optional[Dict] = None,
                 chaos_fabric=None, submit=None):
        self.scenario = scenario
        self.store = store
        self.fleet = fleet
        self.namespace = namespace
        self.clock = clock or VirtualClock(scenario.scale)
        self.chaos_proxy = chaos_proxy
        self.chaos_targets = dict(chaos_targets or {})
        self.chaos_fabric = chaos_fabric
        self._submit = submit
        self.events = scenario.events()
        self.submitted: List[str] = []  # "<ns>/<name>" of created jobs
        # (scenario_t, kind, detail, error | None): the audit trail
        self.executed: List[Tuple[float, str, str, Optional[str]]] = []
        self.chaos: Optional[ChaosController] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ScenarioEngine":
        self._t0 = time.monotonic()
        if self.scenario.chaos is not None:
            targets = dict(self.chaos_targets)
            if self.fleet is not None:
                from mpi_operator_tpu.executor.hollow import HollowNodeTarget

                for name in self.fleet.node_names:
                    targets.setdefault(
                        name, HollowNodeTarget(self.fleet, name)
                    )
            self.chaos = ChaosController(
                self._wall_chaos(self.scenario.chaos),
                proxy=self.chaos_proxy, targets=targets,
                fabric=self.chaos_fabric, store=self.store,
            ).arm()
        self._thread = threading.Thread(
            target=self._run, name="scenario-engine", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.chaos is not None:
            self.chaos.stop()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
        if self.chaos is not None:
            self.chaos.join(timeout)

    def done(self) -> bool:
        return (self._thread is not None and not self._thread.is_alive()
                and (self.chaos is None or self.chaos.done()))

    def errors(self) -> List[str]:
        out = [f"t={t:.0f} {kind} {detail}: {err}"
               for t, kind, detail, err in self.executed if err]
        if self.chaos is not None:
            out += [f"chaos t={t:.1f} {a.fault}: {e}"
                    for t, a, e in self.chaos.executed if e]
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Freeze the engine's store into a :func:`snapshot_store` document
        — the reachable start states ``analysis convcheck`` judges come
        from here."""
        return snapshot_store(self.store)

    def _wall_chaos(self, script: ChaosScript) -> ChaosScript:
        """The embedded fault timeline, converted to wall time: `at`,
        active-rule deadlines AND injected delay amounts all compress —
        a scripted 30s network delay in a 60x day is a 0.5s delay, or
        the compressed run would be proportionally sicker than the day
        it models."""
        acts = [ChaosAction(
            at=self.clock.to_wall(a.at), fault=a.fault, target=a.target,
            match=a.match, prob=a.prob,
            seconds=self.clock.to_wall(a.seconds),
            until=(None if a.until is None
                   else self.clock.to_wall(a.until)),
            a=a.a, b=a.b,
        ) for a in script.actions]
        return ChaosScript(script.seed, acts)

    # -- the timeline walk --------------------------------------------------

    def _run(self) -> None:
        for vt, kind, payload in self.events:
            delay = self._t0 + self.clock.to_wall(vt) - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            err = None
            try:
                self._apply(kind, payload)
            except Exception as e:  # one failed event must not end the day
                err = f"{type(e).__name__}: {e}"
                log.warning("scenario event %s %s failed: %s",
                            kind, payload, err)
            self.executed.append((vt, kind, self._detail(kind, payload),
                                  err))

    @staticmethod
    def _detail(kind: str, payload: Dict[str, Any]) -> str:
        if kind == "serve-qps":
            return f"{payload['serve']}@{payload['qps']}"
        if kind == "submit":
            return payload["name"]
        return repr(payload)

    def _apply(self, kind: str, payload: Dict[str, Any]) -> None:
        if kind == "serve-qps":
            load = getattr(getattr(self.fleet, "timeline", None),
                           "load", None)
            if load is None:
                raise RuntimeError(
                    "serve curves need a fleet whose HollowTimeline "
                    "carries a ServeLoadModel"
                )
            load.set_offered(payload["serve"], payload["qps"])
            return
        if kind == "submit":
            if self._submit is not None:
                self._submit(payload)
            else:
                self._create_job(payload)
            self.submitted.append(f"{self.namespace}/{payload['name']}")
            return
        if kind == "maintenance-wave":
            if self.fleet is None:
                raise RuntimeError("maintenance waves need a fleet")
            from mpi_operator_tpu.executor.hollow import MaintenanceSchedule

            # start_s=0: the wave's own `at` already positioned it; the
            # schedule knobs are scenario seconds — the fleet's clock
            # (threaded through its timer wheel) converts them
            self.fleet.arm_maintenance(MaintenanceSchedule(
                fraction=payload["fraction"],
                notice_s=payload["notice"],
                start_s=0.0,
                stagger_s=payload["stagger"],
                seed=self.scenario.seed,
            ))
            return
        raise RuntimeError(f"unknown scenario event kind {kind!r}")

    def _create_job(self, payload: Dict[str, Any]) -> None:
        from mpi_operator_tpu.api.client import TPUJobClient

        TPUJobClient(self.store).create({
            "kind": "TPUJob",
            "metadata": {
                "name": payload["name"], "namespace": self.namespace,
                "labels": {LABEL_TENANT: payload["tenant"]},
            },
            "spec": {
                "slice": {"accelerator": "cpu",
                          "chips_per_host": payload["chips"]},
                # the admission plane insists the two names for one
                # quantity agree — a multi-chip arrival without this is
                # rejected at create
                "slots_per_worker": payload["chips"],
                "run_policy": {"clean_pod_policy": "None"},
                "worker": {"replicas": payload["pods"], "template": {
                    "containers": [{"image": "soak/noop",
                                    "command": ["true"]}],
                }},
            },
        })


# ---------------------------------------------------------------------------
# store snapshots — the export seam for offline analysis (convcheck)
# ---------------------------------------------------------------------------

SNAPSHOT_VERSION = 1


def snapshot_store(store) -> Dict[str, Any]:
    """Export every object in the store as a plain-dict document.

    The document is the reachable-state seam between the scenario plane and
    offline analysis: ``analysis convcheck`` replays its start-state corpus
    from exactly this shape, so a paused soak run can be frozen mid-rollout /
    mid-drain and judged for convergence without re-running the day."""
    from mpi_operator_tpu.machinery import serialize

    objects = []
    for kind in sorted(serialize.KIND_CLASSES):
        for obj in store.list(kind):
            objects.append({"kind": kind, "object": serialize.encode(obj)})
    return {"version": SNAPSHOT_VERSION, "objects": objects}


def restore_store(store, doc: Dict[str, Any]) -> int:
    """Load a :func:`snapshot_store` document into ``store`` (create-only:
    the target is expected empty). Fails closed — an unknown kind, a wrong
    version or a malformed entry raises :class:`ScenarioError` rather than
    silently building a half-world. Returns the object count."""
    from mpi_operator_tpu.machinery import serialize

    if not isinstance(doc, dict):
        raise ScenarioError(f"snapshot must be a mapping, got "
                            f"{type(doc).__name__}")
    version = doc.get("version")
    if version != SNAPSHOT_VERSION:
        raise ScenarioError(f"unsupported snapshot version {version!r} "
                            f"(want {SNAPSHOT_VERSION})")
    entries = doc.get("objects")
    if not isinstance(entries, list):
        raise ScenarioError("snapshot 'objects' must be a list")
    n = 0
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict) or not isinstance(
                entry.get("object"), dict):
            raise ScenarioError(f"snapshot objects[{i}] is malformed")
        kind = entry.get("kind")
        try:
            obj = serialize.decode(kind, entry["object"])
        except KeyError:
            raise ScenarioError(f"snapshot objects[{i}] has unknown kind "
                                f"{kind!r}") from None
        except Exception as e:
            raise ScenarioError(
                f"snapshot objects[{i}] ({kind}) failed to decode: {e}"
            ) from None
        # the snapshot carries authoritative uids; keep them so uid-pinned
        # patches in the replayed loops still match
        store.create(obj)
        n += 1
    return n


def smoke() -> int:
    """The <30s scenario smoke (verify SKILL.md static gate): a 90-
    scenario-second "day" at 30x compression — a diurnal serve curve, a
    seeded arrival stream, and a rolling maintenance wave — against an
    in-process store + controllers + 4-node hollow fleet. Bars: the
    resolved timeline is deterministic (two resolutions identical), every
    engine event applied cleanly, the serve load model saw a nonzero
    set-point, at least one arrival job Succeeded, and the wave's notice
    landed (a node carries the maintenance annotation). One JSON line;
    exit 0 iff all hold."""
    import json

    from mpi_operator_tpu.api import conditions as cond
    from mpi_operator_tpu.controller.controller import TPUJobController
    from mpi_operator_tpu.controller.disruption import DrainController
    from mpi_operator_tpu.executor.hollow import (
        HollowFleet,
        HollowTimeline,
        ServeLoadModel,
    )
    from mpi_operator_tpu.machinery.events import EventRecorder
    from mpi_operator_tpu.machinery.objects import (
        ANNOTATION_MAINTENANCE_AT,
        NODE_NAMESPACE,
    )
    from mpi_operator_tpu.machinery.store import ObjectStore
    from mpi_operator_tpu.scheduler.gang import GangScheduler

    t0 = time.time()
    doc = {
        "seed": 7, "scale": 30.0, "duration": 90.0,
        "serves": [{"serve": "soak/web", "curve": "diurnal",
                    "peak_qps": 80.0, "trough_qps": 10.0,
                    "period": 90.0, "interval": 15.0}],
        "arrivals": [{"tenant": "etl", "rate_per_hour": 360.0,
                      "pods": 2, "chips": 1, "end": 60.0}],
        "maintenance": [{"at": 30.0, "fraction": 0.25, "notice": 30.0,
                         "stagger": 5.0}],
    }
    scenario = Scenario.parse(doc)
    deterministic = scenario.events() == Scenario.parse(doc).events()
    clock = VirtualClock(scenario.scale)
    store = ObjectStore()
    recorder = EventRecorder(store)
    load = ServeLoadModel()
    ctrl = TPUJobController(store, recorder)
    sched = GangScheduler(store, recorder)
    drain = DrainController(store, recorder, interval=0.1)
    fleet = HollowFleet(
        store, 4, timeline=HollowTimeline(run_s=0.3, load=load),
        capacity_chips=4, heartbeat_interval=0.5, clock=clock,
    )
    ctrl.run()
    sched.start()
    fleet.start()
    drain.start()
    engine = ScenarioEngine(scenario, store, fleet=fleet, clock=clock)
    out = {"metric": "scenario_smoke", "ok": False,
           "events": len(engine.events)}
    try:
        engine.start()
        deadline = time.time() + 20.0
        while time.time() < deadline and not engine.done():
            time.sleep(0.1)
        # let the last arrivals finish their 0.3s scripted run
        deadline = time.time() + 10.0
        succeeded = 0
        while time.time() < deadline:
            succeeded = sum(
                1 for key in engine.submitted
                if cond.is_succeeded(store.get(
                    "TPUJob", *key.split("/", 1)).status)
            )
            if succeeded == len(engine.submitted):
                break
            time.sleep(0.1)
        noticed = [
            n.metadata.name for n in store.list("Node", NODE_NAMESPACE)
            if ANNOTATION_MAINTENANCE_AT in n.metadata.annotations
        ]
        out.update({
            "deterministic": deterministic,
            "submitted": len(engine.submitted),
            "succeeded": succeeded,
            "offered_qps": load.offered("soak/web"),
            "noticed_nodes": len(noticed),
            "errors": engine.errors()[:5],
            "elapsed_s": round(time.time() - t0, 1),
        })
        out["ok"] = bool(
            deterministic
            and engine.done()
            and not engine.errors()
            and engine.submitted
            and succeeded == len(engine.submitted)
            and load.offered("soak/web") > 0
            and noticed
        )
    except Exception as e:
        log.exception("scenario smoke failed")
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        engine.stop()
        drain.stop()
        fleet.stop()
        sched.stop()
        ctrl.stop()
    print(json.dumps(out), flush=True)
    return 0 if out["ok"] else 1


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpu-scenario",
        description="Declarative fleet-scenario engine (the soak bench's "
                    "workload DSL).",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="run the <30s in-process scenario smoke: a 30x-"
                         "compressed 90s day against a hollow fleet; "
                         "exit 0 iff every bar holds")
    ap.add_argument("--validate", metavar="FILE",
                    help="parse a scenario file and print its resolved "
                         "event count (exit 2 on a malformed document)")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke()
    if args.validate:
        import json

        try:
            scenario = Scenario.load(args.validate)
        except ScenarioError as e:
            print(f"invalid scenario: {e}")
            return 2
        events = scenario.events()
        print(json.dumps({
            "ok": True, "seed": scenario.seed, "scale": scenario.scale,
            "duration": scenario.duration, "events": len(events),
            "chaos_actions": (len(scenario.chaos.actions)
                              if scenario.chaos else 0),
        }))
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
