"""Chaos plane: deterministic, scriptable fault injection for the control
plane's seams.

The operator's whole value proposition is surviving churn — gang-coherent
restarts, leader failover, watch relist, client retry/backoff — but until
this module those mechanisms were only ever exercised by happy-path e2e or
unit tests faking one side of the seam. This is the harness that drives
them through REAL failures, reproducibly:

- :class:`ChaosProxy` — an HTTP-aware TCP proxy that sits on the plaintext
  store seam (client ↔ StoreServer) and can **drop**, **delay**, or
  **duplicate** individual requests, **sever** live connections (watch
  streams included — they are classified by their request path), or
  **blackhole** the seam entirely. Probabilistic faults draw from a
  per-connection RNG seeded by ``(script seed, connection index)``, so two
  runs of the same script against the same traffic make the same
  decisions regardless of thread interleaving.
- :class:`ProcessTarget` / :class:`SelfTarget` — process-level fault
  actions: SIGKILL/SIGTERM/restart the store server, an operator replica,
  or a node agent (the crash-recovery scenarios of tests/test_chaos.py).
- :class:`ChaosScript` + :class:`ChaosController` — a scripted timeline
  (YAML/JSON) binding the above to deterministic fire times, so every
  chaos run is a replayable artifact, not a flake generator. The operator
  CLI accepts ``--chaos-script`` and arms the script against itself when
  it becomes leader (the leader-failover scenario kills the leader at a
  fixed offset into its reign).

Script format (YAML or JSON; times are seconds relative to ``arm()``)::

    seed: 42
    actions:
      - {at: 2.0, fault: sever, match: watch}      # cut live watch streams
      - {at: 3.0, fault: blackhole, duration: 1.5} # refuse the seam for 1.5s
      - {at: 5.0, fault: kill, target: store}      # SIGKILL a registered proc
      - {at: 6.5, fault: restart, target: store}   # respawn it
      - {at: 1.0, fault: drop, match: mutation, prob: 0.3, duration: 3.0}
      - {at: 1.0, fault: delay, seconds: 0.05, duration: 3.0}
      - {at: 4.0, fault: duplicate, match: mutation, prob: 1.0, duration: 1.0}
      - {at: 2.0, fault: partition, a: n0, b: n1, duration: 1.5}  # symmetric cut
      - {at: 7.0, fault: reclaim, target: node-3}  # spot reclaim: zero warning

``partition`` is the Jepsen verb: both directions between two NAMED
endpoints blackholed at once, healed on schedule (a duration expands to
an explicit ``heal`` edge). It targets a *fabric* — the in-process
``replicated_store.PeerHub`` for replica-set schedules, or
:class:`NamedProxyFabric` over per-directed-pair proxies for multi-process
deployments — passed to :class:`ChaosController` as ``fabric=``. Like
every other fault, knobs it ignores are rejected at parse time.

Dropped requests are closed BEFORE being forwarded upstream, so the client
observes a transport error for a request the server never saw — the same
ambiguity class as a connection refused, which every client in this
framework already handles (bounded retry/backoff, level-triggered
reconciles). Duplicated requests exercise idempotence: the first response
is swallowed, the second returned, so the server has applied the verb
twice while the client saw it once.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger("tpujob.chaos")

# faults acting on a registered process target
PROCESS_FAULTS = ("kill", "term", "restart")
# faults acting on the proxy seam
PROXY_FAULTS = ("sever", "blackhole", "restore", "drop", "delay",
                "duplicate", "clear")
# faults acting on a fabric (a registry of named endpoints supporting
# symmetric pairwise cuts: replicated_store.PeerHub in-process, or any
# object with partition(a, b)/heal(a, b)) — the Jepsen partition verb
FABRIC_FAULTS = ("partition", "heal")
# faults acting through a store handle (ChaosController store=): the
# planned-disruption verbs. `maintenance` stamps the tpujob.dev/
# maintenance-at notice on Node `target` with `duration` seconds of
# warning, then expands into a `maintenance-fire` edge at the deadline —
# which SIGKILLs the same-named process target IF anything is still
# bound to the node (the cloud provider does not wait for your drain).
# `reclaim` is the spot-instance verb: NO notice window — the deadline
# annotation is stamped already expired and the node's process target is
# SIGKILLed in the same action, so the drain plane only ever sees a dead
# node with a past-due maintenance stamp (its escalation path owns the
# free eviction)
STORE_FAULTS = ("maintenance", "maintenance-fire", "reclaim")
MATCHES = ("any", "watch", "mutation", "read")


class ChaosScriptError(ValueError):
    """Malformed chaos script (fail fast: a typo'd fault name silently doing
    nothing would make a 'passing' chaos run meaningless)."""


# which optional knobs each fault actually consumes — anything else in the
# action is rejected at parse time for the same fail-fast reason: a knob
# the runner ignores ('duration' on a sever, 'prob' on a kill) would make
# the script claim more chaos than it injects
_FAULT_KNOBS: Dict[str, frozenset] = {
    "kill": frozenset({"target"}),
    "term": frozenset({"target"}),
    "restart": frozenset({"target"}),
    "sever": frozenset({"match"}),
    "blackhole": frozenset({"duration"}),
    "restore": frozenset(),
    "clear": frozenset(),
    "drop": frozenset({"match", "prob", "duration"}),
    "delay": frozenset({"match", "prob", "seconds", "duration"}),
    "duplicate": frozenset({"match", "prob", "duration"}),
    # partition is SYMMETRIC (both directions blackholed) between two
    # NAMED endpoints; a duration expands into an explicit heal action so
    # the executed log shows both edges (same treatment as blackhole)
    "partition": frozenset({"a", "b", "duration"}),
    "heal": frozenset({"a", "b"}),
    # maintenance: target names BOTH the Node object to stamp and the
    # process registry entry to SIGKILL at the deadline; duration is the
    # notice window (required: a notice with no deadline is not a fault)
    "maintenance": frozenset({"target", "duration"}),
    "maintenance-fire": frozenset({"target"}),
    # reclaim takes NO duration by construction: a notice window would
    # make it maintenance. Passing one is rejected at parse, not ignored.
    "reclaim": frozenset({"target"}),
}


# ---------------------------------------------------------------------------
# script
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ChaosAction:
    at: float                      # seconds after arm()
    fault: str
    target: str = ""               # process faults: registry name
    match: str = "any"             # any | watch | mutation | read | /path-prefix
    prob: float = 1.0              # drop/duplicate: per-request probability
    seconds: float = 0.0           # delay: added latency per request
    until: Optional[float] = None  # rule faults: deactivate at this offset
    a: str = ""                    # fabric faults: the two endpoint names
    b: str = ""


class ChaosScript:
    """A validated, ordered fault timeline. Parse once, run anywhere —
    the same script object drives both runs of a determinism check."""

    def __init__(self, seed: int, actions: List[ChaosAction]):
        self.seed = seed
        self.actions = sorted(actions, key=lambda a: a.at)

    @classmethod
    def parse(cls, doc: Dict[str, Any]) -> "ChaosScript":
        if not isinstance(doc, dict):
            raise ChaosScriptError("chaos script must be a mapping")
        seed = doc.get("seed", 0)
        if not isinstance(seed, int):
            raise ChaosScriptError(f"seed must be an integer, got {seed!r}")
        raw = doc.get("actions")
        if not isinstance(raw, list) or not raw:
            raise ChaosScriptError("chaos script needs a non-empty 'actions' list")
        actions: List[ChaosAction] = []
        for i, a in enumerate(raw):
            if not isinstance(a, dict):
                raise ChaosScriptError(f"actions[{i}]: must be a mapping")
            unknown = set(a) - {"at", "fault", "target", "match", "prob",
                                "seconds", "duration", "a", "b"}
            if unknown:
                raise ChaosScriptError(
                    f"actions[{i}]: unknown keys {sorted(unknown)}"
                )
            try:
                at = float(a["at"])
                fault = str(a["fault"])
            except (KeyError, TypeError, ValueError):
                raise ChaosScriptError(
                    f"actions[{i}]: 'at' (seconds) and 'fault' are required"
                ) from None
            if at < 0:
                raise ChaosScriptError(f"actions[{i}]: at must be >= 0")
            known = (PROCESS_FAULTS + PROXY_FAULTS + FABRIC_FAULTS
                     + STORE_FAULTS)
            if fault not in known:
                raise ChaosScriptError(
                    f"actions[{i}]: unknown fault {fault!r} (known: "
                    f"{', '.join(known)})"
                )
            inapplicable = set(a) - {"at", "fault"} - _FAULT_KNOBS[fault]
            if inapplicable:
                raise ChaosScriptError(
                    f"actions[{i}]: {sorted(inapplicable)} do(es) not apply "
                    f"to fault {fault!r} (it would be silently ignored; "
                    f"valid knobs: {sorted(_FAULT_KNOBS[fault]) or 'none'})"
                )
            target = str(a.get("target", ""))
            if fault in PROCESS_FAULTS + STORE_FAULTS and not target:
                raise ChaosScriptError(
                    f"actions[{i}]: fault {fault!r} needs a 'target'"
                )
            end_a = str(a.get("a", ""))
            end_b = str(a.get("b", ""))
            if fault in FABRIC_FAULTS:
                if not end_a or not end_b or end_a == end_b:
                    raise ChaosScriptError(
                        f"actions[{i}]: fault {fault!r} needs two distinct "
                        f"endpoint names 'a' and 'b'"
                    )
            match = str(a.get("match", "any"))
            if match not in MATCHES and not match.startswith("/"):
                raise ChaosScriptError(
                    f"actions[{i}]: match must be one of {MATCHES} or a "
                    f"'/path' prefix, got {match!r}"
                )
            prob = float(a.get("prob", 1.0))
            if not 0.0 <= prob <= 1.0:
                raise ChaosScriptError(f"actions[{i}]: prob must be in [0, 1]")
            seconds = float(a.get("seconds", 0.0))
            duration = float(a.get("duration", 0.0))
            until = at + duration if duration > 0 else None
            if fault == "blackhole" and until is not None:
                # expand the window into an explicit restore action so the
                # executed log shows both edges
                actions.append(ChaosAction(at=at, fault="blackhole"))
                actions.append(ChaosAction(at=until, fault="restore"))
                continue
            if fault == "partition" and until is not None:
                actions.append(ChaosAction(at=at, fault="partition",
                                           a=end_a, b=end_b))
                actions.append(ChaosAction(at=until, fault="heal",
                                           a=end_a, b=end_b))
                continue
            if fault == "maintenance":
                if duration <= 0:
                    raise ChaosScriptError(
                        f"actions[{i}]: fault 'maintenance' needs a "
                        f"positive 'duration' (the notice window before "
                        f"the deadline SIGKILL)"
                    )
                # notice now, fire at the deadline: both edges land in the
                # executed log (the blackhole/partition treatment). The
                # notice action carries the window in `seconds` so it can
                # stamp deadline = apply-time + window.
                actions.append(ChaosAction(at=at, fault="maintenance",
                                           target=target,
                                           seconds=duration))
                actions.append(ChaosAction(at=at + duration,
                                           fault="maintenance-fire",
                                           target=target))
                continue
            actions.append(ChaosAction(
                at=at, fault=fault, target=target, match=match, prob=prob,
                seconds=seconds, until=until, a=end_a, b=end_b,
            ))
        return cls(seed, actions)

    @classmethod
    def load(cls, path: str) -> "ChaosScript":
        import yaml  # YAML is a superset of JSON: one loader serves both

        with open(path) as f:
            try:
                doc = yaml.safe_load(f)
            except yaml.YAMLError as e:
                raise ChaosScriptError(f"{path}: {e}") from None
        try:
            return cls.parse(doc)
        except ChaosScriptError as e:
            raise ChaosScriptError(f"{path}: {e}") from None


# ---------------------------------------------------------------------------
# process targets
# ---------------------------------------------------------------------------


class ProcessTarget:
    """A killable/restartable subprocess (store server, operator replica,
    node agent). ``spawn`` returns a fresh ``subprocess.Popen``; ``proc``
    seeds the currently-running instance."""

    def __init__(self, spawn: Callable[[], Any], proc: Any = None):
        self.spawn = spawn
        self.proc = proc

    def _signal(self, sig: int) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(sig)
            if sig == signal.SIGKILL:
                self.proc.wait()  # SIGKILL is not ignorable: reap promptly

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def term(self) -> None:
        self._signal(signal.SIGTERM)

    def restart(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.kill()
        self.proc = self.spawn()


class SelfTarget:
    """The current process as a fault target (the operator's
    ``--chaos-script`` self-destruct: SIGKILL mid-reign is how the
    leader-failover e2e makes 'the leader dies mid-reconcile' a
    deterministic, scripted event instead of a manual race)."""

    def kill(self) -> None:
        os.kill(os.getpid(), signal.SIGKILL)

    def term(self) -> None:
        os.kill(os.getpid(), signal.SIGTERM)

    def restart(self) -> None:
        raise RuntimeError("the current process cannot restart itself")


# ---------------------------------------------------------------------------
# HTTP-aware proxy
# ---------------------------------------------------------------------------


def _read_http_message(
    rfile, what: str
) -> Optional[Tuple[bytes, str, Dict[str, str]]]:
    """Read one framed HTTP/1.1 message (start line + headers +
    Content-Length body — the only framing the store server emits).
    Returns (raw bytes, start line, headers) or None on clean EOF at a
    message boundary."""
    start = rfile.readline(65536)
    while start in (b"\r\n", b"\n"):  # tolerate stray separators
        start = rfile.readline(65536)
    if not start:
        return None
    chunks = [start]
    headers: Dict[str, str] = {}
    while True:
        line = rfile.readline(65536)
        if not line:
            raise ConnectionError(f"EOF inside {what} headers")
        chunks.append(line)
        if line in (b"\r\n", b"\n"):
            break
        key, _, val = line.decode("latin-1").partition(":")
        headers[key.strip().lower()] = val.strip()
    try:
        length = int(headers.get("content-length", "0") or "0")
    except ValueError:
        raise ConnectionError(f"bad {what} Content-Length") from None
    if length:
        body = rfile.read(length)
        if len(body) < length:
            raise ConnectionError(f"EOF inside {what} body")
        chunks.append(body)
    return b"".join(chunks), start.decode("latin-1").strip(), headers


def _classify(request_line: str) -> Tuple[str, str]:
    """(class, path) of a request line: 'watch' for the long-poll route,
    'mutation' for write verbs, 'read' otherwise."""
    parts = request_line.split(" ")
    method = parts[0] if parts else ""
    path = parts[1] if len(parts) > 1 else ""
    bare = path.split("?", 1)[0]
    if bare == "/v1/watch":
        return "watch", bare
    if method in ("POST", "PUT", "PATCH", "DELETE"):
        return "mutation", bare
    return "read", bare


def _matches(match: str, klass: str, path: str) -> bool:
    if match == "any":
        return True
    if match.startswith("/"):
        return path.startswith(match)
    return match == klass


@dataclass
class _Rule:
    fault: str          # drop | delay | duplicate
    match: str = "any"
    prob: float = 1.0
    seconds: float = 0.0
    until: Optional[float] = None  # monotonic deadline; None = forever


class _ProxyConn(threading.Thread):
    """One proxied client connection: parse requests, apply fault rules,
    forward over a dedicated upstream connection, relay responses."""

    def __init__(self, proxy: "ChaosProxy", client: socket.socket, conn_id: int):
        super().__init__(name=f"chaos-conn-{conn_id}", daemon=True)
        self.proxy = proxy
        self.client = client
        self.conn_id = conn_id
        self.klass = "idle"  # class of the most recent request (sever match)
        # per-connection RNG: decisions replay identically for the same
        # (seed, connection index) regardless of thread interleaving
        self.rng = random.Random(f"{proxy.seed}:{conn_id}")
        self.upstream: Optional[socket.socket] = None
        self.upstream_rfile = None
        self._dead = threading.Event()

    # -- plumbing -----------------------------------------------------------

    def _connect_upstream(self):
        s = socket.create_connection(self.proxy.upstream_addr, timeout=10.0)
        s.settimeout(self.proxy.upstream_timeout)
        self.upstream = s
        # ONE buffered reader for the connection's lifetime: a fresh
        # makefile per response would read-ahead into its private buffer
        # and swallow the start of the next response (keep-alive framing)
        self.upstream_rfile = s.makefile("rb")
        return s

    def sever(self) -> None:
        """Hard-close both sides (the fault, not cleanup: the peer sees a
        reset mid-exchange, exactly what a network partition looks like)."""
        self._dead.set()
        for s in (self.client, self.upstream):
            if s is not None:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass

    # -- request loop -------------------------------------------------------

    def run(self) -> None:
        try:
            self.client.settimeout(self.proxy.client_timeout)
            crfile = self.client.makefile("rb")
            while not self._dead.is_set() and not self.proxy._stop.is_set():
                msg = _read_http_message(crfile, "request")
                if msg is None:
                    break
                raw, line, _headers = msg
                self.klass, path = _classify(line)
                if self.proxy._blackhole.is_set():
                    self.proxy._count("blackholed")
                    break  # close without forwarding
                faults = self.proxy._decide(self.rng, self.klass, path)
                if "drop" in faults:
                    self.proxy._count("dropped")
                    break  # request never reaches the server
                if faults.get("delay"):
                    # oplint: disable=BLK001 — the sleep IS the injected
                    # fault (ChaosScript delay_ms); bounding it would change
                    # the failure being simulated
                    time.sleep(faults["delay"])
                    self.proxy._count("delayed")
                copies = 2 if "duplicate" in faults else 1
                resp = self._forward(raw, copies)
                if resp is None:
                    break
                if copies == 2:
                    self.proxy._count("duplicated")
                try:
                    self.client.sendall(resp)
                except OSError:
                    break
                self.proxy._count("forwarded")
        except (ConnectionError, OSError, ValueError):
            pass  # severed / reset / timed out: the fault did its job
        finally:
            self.sever()
            self.proxy._forget(self)

    def _close_upstream(self) -> None:
        if self.upstream is not None:
            try:
                self.upstream.close()
            except OSError:
                pass
        self.upstream = None
        self.upstream_rfile = None

    def _forward(self, raw: bytes, copies: int) -> Optional[bytes]:
        """Send the request ``copies`` times upstream; return the LAST
        response's bytes (duplicate swallows the first — the server applied
        the verb twice, the client sees one response). Clients send
        ``Connection: close`` per request (urllib), so each copy may need a
        fresh upstream connection; a copy is retried once on a dead
        connection and the failure is otherwise relayed by dropping the
        client (a mid-exchange upstream kill IS the injected fault)."""
        resp = None
        for _ in range(copies):
            msg = None
            for attempt in (0, 1):
                if self.upstream is None:
                    try:
                        self._connect_upstream()
                    except OSError:
                        return None
                try:
                    self.upstream.sendall(raw)
                    msg = _read_http_message(self.upstream_rfile, "response")
                except (ConnectionError, OSError):
                    msg = None
                if msg is not None:
                    break
                self._close_upstream()  # stale keep-alive: reconnect once
            if msg is None:
                return None
            resp, _, rheaders = msg
            if rheaders.get("connection", "").lower() == "close":
                self._close_upstream()
        return resp


class ChaosProxy:
    """An HTTP-aware fault-injecting proxy for the plaintext store seam.

    Point clients at :attr:`url` instead of the real server; drive faults
    directly (:meth:`sever`, :meth:`set_blackhole`, :meth:`add_rule`) or
    through a :class:`ChaosController` timeline."""

    def __init__(self, upstream_url: str, host: str = "127.0.0.1",
                 port: int = 0, *, seed: int = 0):
        if not upstream_url.startswith("http://"):
            raise ValueError(
                "ChaosProxy fronts the plaintext seam only (an https "
                "upstream would require MITM certificates)"
            )
        hostport = upstream_url[len("http://"):].rstrip("/")
        uhost, _, uport = hostport.rpartition(":")
        self.upstream_addr = (uhost.strip("[]") or "127.0.0.1", int(uport))
        self.seed = seed
        self.client_timeout = 120.0
        self.upstream_timeout = 90.0  # > the 55s watch long-poll cap
        self._listen = socket.create_server((host, port))
        self.host, self.port = self._listen.getsockname()[:2]
        self._stop = threading.Event()
        self._blackhole = threading.Event()
        self._lock = threading.Lock()
        self._conns: List[_ProxyConn] = []
        self._next_conn = 0
        self._rules: List[_Rule] = []
        self.stats: Dict[str, int] = {
            "forwarded": 0, "dropped": 0, "delayed": 0, "duplicated": 0,
            "severed": 0, "blackholed": 0, "connections": 0,
        }
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="chaos-proxy-accept", daemon=True
        )

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ChaosProxy":
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listen.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.sever()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listen.accept()
            except OSError:
                return  # listener closed
            if self._blackhole.is_set():
                self._count("blackholed")
                try:
                    client.close()
                except OSError:
                    pass
                continue
            with self._lock:
                conn = _ProxyConn(self, client, self._next_conn)
                self._next_conn += 1
                self._conns.append(conn)
                self.stats["connections"] += 1
            conn.start()

    def _forget(self, conn: _ProxyConn) -> None:
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)

    def _count(self, what: str) -> None:
        with self._lock:
            self.stats[what] = self.stats.get(what, 0) + 1

    # -- fault surface ------------------------------------------------------

    def sever(self, match: str = "any") -> int:
        """Hard-close live connections whose latest request matches (the
        'network partition mid-exchange' fault; 'watch' cuts long-polls)."""
        with self._lock:
            # connection-level fault: class matches only (a path prefix has
            # no meaning for an idle keep-alive connection) — '/...' severs
            # everything, like 'any'
            victims = [
                c for c in self._conns
                if match.startswith("/") or _matches(match, c.klass, "")
            ]
        for c in victims:
            c.sever()
            self._count("severed")
        return len(victims)

    def set_blackhole(self, on: bool) -> None:
        """While on, new connections are closed at accept and in-flight
        connections drop their next request — the seam is gone."""
        if on:
            self._blackhole.set()
        else:
            self._blackhole.clear()

    def add_rule(self, fault: str, *, match: str = "any", prob: float = 1.0,
                 seconds: float = 0.0, until: Optional[float] = None) -> None:
        if fault not in ("drop", "delay", "duplicate"):
            raise ValueError(f"unknown proxy rule fault {fault!r}")
        with self._lock:
            self._rules.append(_Rule(fault, match, prob, seconds, until))

    def clear_rules(self) -> None:
        with self._lock:
            self._rules.clear()

    def _decide(self, rng: random.Random, klass: str, path: str) -> Dict[str, Any]:
        """Evaluate active rules against one request. The RNG is consulted
        for EVERY matching probabilistic rule whether or not an earlier rule
        already fired — the draw sequence per connection depends only on its
        request sequence, keeping replays aligned."""
        now = time.monotonic()
        out: Dict[str, Any] = {}
        with self._lock:
            rules = list(self._rules)
        for r in rules:
            if r.until is not None and now > r.until:
                continue
            if not _matches(r.match, klass, path):
                continue
            fired = r.prob >= 1.0 or rng.random() < r.prob
            if not fired:
                continue
            if r.fault == "delay":
                out["delay"] = out.get("delay", 0.0) + r.seconds
            else:
                out[r.fault] = True
        return out


class NamedProxyFabric:
    """Adapts per-directed-pair :class:`ChaosProxy` instances to the
    partition fabric surface: register the proxy carrying a→b traffic
    under ``"a->b"``; ``partition(a, b)`` then blackholes BOTH directions
    (and severs their live connections), ``heal`` restores both — the
    multi-process twin of ``replicated_store.PeerHub.partition``. Missing
    links fail loudly: a partition that silently cut nothing would make a
    'passing' chaos run meaningless (the ChaosScript fail-fast rule)."""

    def __init__(self, links: Dict[str, ChaosProxy]):
        self.links = dict(links)

    def _pair(self, a: str, b: str) -> List[ChaosProxy]:
        out = []
        for key in (f"{a}->{b}", f"{b}->{a}"):
            if key not in self.links:
                raise KeyError(f"no proxy registered for link {key!r}")
            out.append(self.links[key])
        return out

    def partition(self, a: str, b: str) -> None:
        for proxy in self._pair(a, b):
            proxy.set_blackhole(True)
            proxy.sever("any")

    def heal(self, a: str, b: str) -> None:
        for proxy in self._pair(a, b):
            proxy.set_blackhole(False)


# ---------------------------------------------------------------------------
# timeline driver
# ---------------------------------------------------------------------------


class ChaosController:
    """Executes a :class:`ChaosScript` against a proxy and/or process
    targets on a deterministic wall-clock timeline. ``executed`` records
    (elapsed, action, error) for every fired action — a chaos run leaves an
    audit trail just like the control plane it torments."""

    def __init__(self, script: ChaosScript, *,
                 proxy: Optional[ChaosProxy] = None,
                 targets: Optional[Dict[str, Any]] = None,
                 fabric: Any = None,
                 store: Any = None):
        self.script = script
        self.proxy = proxy
        # the partition/heal surface: anything with partition(a, b) and
        # heal(a, b) — replicated_store.PeerHub, or a NamedProxyFabric
        # over per-directed-pair ChaosProxy instances
        self.fabric = fabric
        # the store handle maintenance faults stamp notices through (an
        # admin-tier client: the annotation is a metadata write)
        self.store = store
        self.targets = dict(targets or {})
        self.executed: List[Tuple[float, ChaosAction, Optional[str]]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    def arm(self) -> "ChaosController":
        """Start the timeline; action times are relative to this call."""
        self._t0 = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="chaos-timeline", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()

    def _run(self) -> None:
        for action in self.script.actions:
            delay = self._t0 + action.at - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            err = None
            try:
                self._apply(action)
            except Exception as e:  # a failed action must not end the run
                err = f"{type(e).__name__}: {e}"
                log.warning("chaos action %s failed: %s", action, err)
            self.executed.append((time.monotonic() - self._t0, action, err))
            log.info("chaos: t=%.2fs %s%s", time.monotonic() - self._t0,
                     action.fault,
                     f" target={action.target}" if action.target else "")

    def _apply(self, a: ChaosAction) -> None:
        if a.fault in STORE_FAULTS:
            self._apply_maintenance(a)
            return
        if a.fault in PROCESS_FAULTS:
            target = self.targets.get(a.target)
            if target is None:
                raise KeyError(f"no process target {a.target!r} registered")
            getattr(target, {"kill": "kill", "term": "term",
                             "restart": "restart"}[a.fault])()
            return
        if a.fault in FABRIC_FAULTS:
            if self.fabric is None:
                raise RuntimeError(f"fault {a.fault!r} needs a fabric")
            getattr(self.fabric, a.fault)(a.a, a.b)
            return
        if self.proxy is None:
            raise RuntimeError(f"fault {a.fault!r} needs a ChaosProxy")
        if a.fault == "sever":
            self.proxy.sever(a.match)
        elif a.fault == "blackhole":
            self.proxy.set_blackhole(True)
        elif a.fault == "restore":
            self.proxy.set_blackhole(False)
        elif a.fault == "clear":
            self.proxy.clear_rules()
        else:  # drop | delay | duplicate
            until = None
            if a.until is not None:
                until = self._t0 + a.until
            self.proxy.add_rule(
                a.fault, match=a.match, prob=a.prob, seconds=a.seconds,
                until=until,
            )

    def _apply_maintenance(self, a: ChaosAction) -> None:
        """The planned-disruption verbs. `maintenance` stamps the notice
        annotation on Node `target` (deadline = now + window); at the
        deadline `maintenance-fire` checks the store — if ANY live pod is
        still bound, the same-named process target is SIGKILLed (the
        provider reclaims the host whether or not the drain finished). A
        clean fire (node already empty) is the drain plane doing its job."""
        if self.store is None:
            raise RuntimeError(
                f"fault {a.fault!r} needs a store= handle on the "
                f"ChaosController"
            )
        # the shared notice contract — imported, not retyped, so a rename
        # breaks loudly instead of stamping a key nobody watches
        from mpi_operator_tpu.machinery.objects import (
            ANNOTATION_MAINTENANCE_AT,
            NODE_NAMESPACE,
        )

        if a.fault == "maintenance":
            deadline = time.time() + a.seconds
            self.store.patch(
                "Node", NODE_NAMESPACE, a.target,
                {"metadata": {"annotations": {
                    ANNOTATION_MAINTENANCE_AT: str(deadline),
                }}},
            )
            log.warning("chaos: maintenance notice on node %s "
                        "(deadline in %.1fs)", a.target, a.seconds)
            return
        if a.fault == "reclaim":
            # the spot-instance reclaim: no warning, no drain window. The
            # deadline is stamped ALREADY EXPIRED so the disruption plane
            # classifies the loss as planned (evictions stay free — no
            # burned restart_count), and the node target dies in the same
            # breath. A missing target fails loudly: a reclaim that kills
            # nothing would make a 'passing' chaos run meaningless.
            target = self.targets.get(a.target)
            if target is None:
                raise KeyError(
                    f"no process target {a.target!r} registered to reclaim"
                )
            self.store.patch(
                "Node", NODE_NAMESPACE, a.target,
                {"metadata": {"annotations": {
                    ANNOTATION_MAINTENANCE_AT: str(time.time()),
                }}},
            )
            log.warning("chaos: reclaiming node %s (zero warning)", a.target)
            target.kill()
            return
        # maintenance-fire
        still_bound = [
            p for p in self.store.list("Pod")
            if p.spec.node_name == a.target and not p.is_finished()
        ]
        if not still_bound:
            log.info("chaos: maintenance fired on empty node %s "
                     "(drain completed in time)", a.target)
            return
        target = self.targets.get(a.target)
        if target is None:
            raise KeyError(
                f"maintenance deadline on {a.target!r} found "
                f"{len(still_bound)} pod(s) still bound but no process "
                f"target of that name is registered to SIGKILL"
            )
        log.warning("chaos: maintenance deadline on %s with %d pod(s) "
                    "still bound — SIGKILL", a.target, len(still_bound))
        target.kill()
