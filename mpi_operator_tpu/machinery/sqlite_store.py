"""SqliteStore: the shared/persistent ObjectStore backend.

The round-1 store was purely in-process, which made the deployment surface
unreachable: leader election elected a leader of nothing because two
operator replicas could never share the lock (VERDICT r1, Missing #1 /
Weak #4). This backend is the seam: the same CRUD/watch surface as
``machinery.store.ObjectStore``, backed by one sqlite file (WAL mode), so
**separate processes** — operator replicas, a CLI submitting jobs, an
executor — observe one consistent store with optimistic concurrency.

≙ the kube-apiserver+etcd role in the reference deployment
(/root/reference/manifests/base/deployment.yaml): durability, a global
resourceVersion sequence, conflict-on-stale-update, and watchable change
feeds. Watches are served from a write-ahead ``log`` table polled by a
background thread (the informer relist/watch trick — poll interval is the
staleness bound, default 50 ms).

Scope: a single-node multi-process deployment target (sqlite serializes
writers via the database lock). A multi-node etcd/k8s adapter would slot
into the same duck-typed surface; components only see create/get/update/
delete/list/watch.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import queue
import sqlite3
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.serialize import decode, encode
from mpi_operator_tpu.machinery.store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExists,
    Conflict,
    NotFound,
    WatchEvent,
    apply_merge_patch_dict,
    patch_batch_via_loop,
)
from mpi_operator_tpu.machinery.yieldpoints import yield_point

log = logging.getLogger("tpujob.sqlite")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS objects (
    kind TEXT NOT NULL,
    namespace TEXT NOT NULL,
    name TEXT NOT NULL,
    rv INTEGER NOT NULL,
    data TEXT NOT NULL,
    PRIMARY KEY (kind, namespace, name)
);
CREATE TABLE IF NOT EXISTS log (
    rv INTEGER PRIMARY KEY AUTOINCREMENT,
    etype TEXT NOT NULL,
    kind TEXT NOT NULL,
    data TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS watch_cursors (
    id TEXT PRIMARY KEY,
    last_rv INTEGER NOT NULL,
    updated REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS replica_meta (
    key TEXT PRIMARY KEY,
    value TEXT NOT NULL
);
"""


class LogTruncated(RuntimeError):
    """A requested log tail starts past the retention horizon: the rows
    were trimmed, so the caller cannot ship an incremental tail and must
    fall back to a full snapshot transfer (replicated_store resync)."""


def entry_hash(entry: Dict[str, Any]) -> str:
    """Content fingerprint of one replication log entry. Rv equality
    alone cannot detect a divergent history (an unacked suffix from a
    dead leader reuses the same rv numbers); the hash can."""
    h = hashlib.sha256()
    h.update(f"{entry['rv']}|{entry['etype']}|{entry['kind']}|".encode())
    h.update(entry["data"].encode())
    return h.hexdigest()[:16]


class SqliteStore:
    """Drop-in ObjectStore over a sqlite file; safe across processes."""

    def __init__(
        self,
        path: str,
        *,
        poll_interval: float = 0.05,
        log_retention_rows: int = 4096,
        cursor_stale_after: float = 60.0,
    ):
        self.path = os.path.abspath(path)
        self.poll_interval = poll_interval
        # retention: the log table is append-only and would otherwise grow
        # (and slow the 50ms poll scan) without bound on a busy operator.
        # Rows are trimmed once every live watcher (this process or another
        # one, tracked in watch_cursors) has consumed them; a cursor whose
        # heartbeat is older than ``cursor_stale_after`` belongs to a dead
        # process and no longer holds rows. ``log_retention_rows`` is the
        # floor kept regardless, so brand-new watchers never race the trim.
        self.log_retention_rows = log_retention_rows
        self.cursor_stale_after = cursor_stale_after
        self._cursor_id = f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self._last_trim = 0.0
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(
            self.path, check_same_thread=False, timeout=30.0
        )
        # durability stance (documented in README "Fuzzing the store
        # seam"): WAL + synchronous=NORMAL. A PROCESS crash (SIGKILL —
        # what the chaos plane injects) loses nothing: every commit's WAL
        # frames are in the OS page cache. An OS/power crash may lose the
        # newest commits (the WAL tail is not fsynced per commit) but
        # never corrupts: recovery lands on a committed PREFIX. The
        # crash-point explorer (analysis/crashpoints.py) pins both halves
        # of this contract — exact snapshots must keep every acked write
        # at its exact rv; torn-tail snapshots model the unsynced-tail
        # loss and are the gated `crash:torn-tail` allowlist exception.
        # Both pragmas are the init-time durability stance, set before
        # any data exists and before a yieldpoints hook can be attached;
        # not transactions the crash-point explorer needs to see (it
        # snapshots AFTER open, when both have landed) — hence the
        # per-line DUR001 disables.
        self._conn.execute("PRAGMA journal_mode=WAL")  # oplint: disable=DUR001
        self._conn.execute("PRAGMA synchronous=NORMAL")  # oplint: disable=DUR001
        with self._txn("schema") as cur:
            cur.executescript(_SCHEMA)
        # probe JSON1 exactly once, at init: selector lists compile to
        # json_each SQL only when the build has it. Probing here (not by
        # catching OperationalError in list()) matters because transient
        # operational errors — 'database is locked' — must keep propagating
        # as such, not silently demote every future selector list to the
        # O(cluster) python-filter path.
        try:
            with self._lock:
                self._conn.execute("SELECT 1 FROM json_each('{}')")
            self._json1 = True
        except sqlite3.OperationalError:
            self._json1 = False
        self._watchers: List[Tuple[Optional[str], "queue.Queue[WatchEvent]"]] = []
        self._relist_listeners: List = []
        self._poller: Optional[threading.Thread] = None
        self._stop = threading.Event()
        with self._lock:
            row = self._conn.execute("SELECT MAX(rv) FROM log").fetchone()
        self._last_seen_rv = row[0] or 0
        # rv → ((trace_id, span_id) | None, commit ts): the causal origin
        # of each committed write, consulted by the poll loop when it emits
        # the corresponding watch event. In-process only (the poller and
        # the writers share this instance; a SEPARATE process polling the
        # same file sees untraced events, which degrades to 'no link', not
        # an error). Bounded FIFO — the poller runs at 50ms, so 4096 rvs of
        # slack is minutes of burst headroom.
        self._origin_lock = threading.Lock()
        self._origins: Dict[int, Tuple[Any, float]] = {}

    # -- helpers -------------------------------------------------------------

    @contextlib.contextmanager
    def _txn(self, what: str = ""):
        """THE sanctioned write transaction: every mutation of the sqlite
        file goes through this helper (oplint DUR001 enforces it) — one
        lock-held ``with self._conn`` block yielding a cursor, announcing
        the transaction boundary through :func:`yield_point` before entry
        (``sqlite.txn``) and after the commit lands (``sqlite.commit``).
        Those two announcements are the os-write/commit seam the ALICE
        crash-point explorer (analysis/crashpoints.py) interposes on: at
        each, the db/WAL bytes are a state a crash could strand on disk.
        On an exception the transaction rolls back and the commit point
        (correctly) never fires."""
        yield_point("sqlite.txn", what)
        with self._lock, self._conn:
            yield self._conn.cursor()
        yield_point("sqlite.commit", what)

    @staticmethod
    def _dump(obj: Any) -> str:
        return json.dumps(encode(obj), sort_keys=True)

    @staticmethod
    def _load(kind: str, data: str) -> Any:
        return decode(kind, json.loads(data))

    def _log(self, cur, etype: str, obj: Any) -> int:
        cur.execute(
            "INSERT INTO log (etype, kind, data) VALUES (?, ?, ?)",
            (etype, obj.kind, self._dump(obj)),
        )
        rv = cur.lastrowid
        # remember the writing span (trace seam) so the poll loop can stamp
        # the watch event this row becomes; None-cheap when tracing is off
        with self._origin_lock:
            self._origins[rv] = (trace.current_ids(), time.time())
            while len(self._origins) > 4096:
                self._origins.pop(next(iter(self._origins)))
        return rv

    def _origin_for(self, rv: int) -> Tuple[Any, float]:
        with self._origin_lock:
            return self._origins.get(rv, (None, 0.0))

    # -- CRUD (same contracts as ObjectStore) --------------------------------

    def create(self, obj: Any) -> Any:
        yield_point("store.create", obj.kind)
        obj = obj.deepcopy()
        m = obj.metadata
        with self._txn("create") as cur:
            row = cur.execute(
                "SELECT 1 FROM objects WHERE kind=? AND namespace=? AND name=?",
                (obj.kind, m.namespace, m.name),
            ).fetchone()
            if row is not None:
                raise AlreadyExists(
                    f"{obj.kind} {m.namespace}/{m.name} already exists"
                )
            if not m.uid:
                m.uid = str(uuid.uuid4())
            if m.creation_timestamp is None:
                m.creation_timestamp = time.time()
            # two inserts: the log row allocates the global rv
            rv = self._log(cur, ADDED, obj)
            m.resource_version = rv
            cur.execute(
                "UPDATE log SET data=? WHERE rv=?", (self._dump(obj), rv)
            )
            cur.execute(
                "INSERT INTO objects (kind, namespace, name, rv, data) "
                "VALUES (?, ?, ?, ?, ?)",
                (obj.kind, m.namespace, m.name, rv, self._dump(obj)),
            )
        return obj.deepcopy()

    def get(self, kind: str, namespace: str, name: str) -> Any:
        yield_point("store.get", name)
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM objects WHERE kind=? AND namespace=? AND name=?",
                (kind, namespace, name),
            ).fetchone()
        if row is None:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        return self._load(kind, row[0])

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def update(self, obj: Any, force: bool = False) -> Any:
        yield_point("store.put", obj.kind)
        obj = obj.deepcopy()
        m = obj.metadata
        with self._txn("update") as cur:
            row = cur.execute(
                "SELECT rv FROM objects WHERE kind=? AND namespace=? AND name=?",
                (obj.kind, m.namespace, m.name),
            ).fetchone()
            if row is None:
                raise NotFound(f"{obj.kind} {m.namespace}/{m.name} not found")
            if not force and m.resource_version != row[0]:
                raise Conflict(
                    f"{obj.kind} {m.namespace}/{m.name}: resource_version "
                    f"{m.resource_version} != {row[0]}"
                )
            rv = self._log(cur, MODIFIED, obj)
            m.resource_version = rv
            cur.execute(
                "UPDATE log SET data=? WHERE rv=?", (self._dump(obj), rv)
            )
            cur.execute(
                "UPDATE objects SET rv=?, data=? "
                "WHERE kind=? AND namespace=? AND name=?",
                (rv, self._dump(obj), obj.kind, m.namespace, m.name),
            )
        return obj.deepcopy()

    def patch(
        self,
        kind: str,
        namespace: str,
        name: str,
        patch: Any,
        *,
        subresource: Optional[str] = None,
    ) -> Any:
        """Merge-patch applied inside one sqlite transaction (read-merge-
        write under the database lock): rv precondition, identity freeze
        and the status subresource come from the shared
        apply_merge_patch_dict core, so semantics match ObjectStore
        exactly. The log row allocates the fresh global rv like any
        update."""
        yield_point("store.patch", name)
        with self._txn("patch") as cur:
            row = cur.execute(
                "SELECT rv, data FROM objects "
                "WHERE kind=? AND namespace=? AND name=?",
                (kind, namespace, name),
            ).fetchone()
            if row is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            merged = apply_merge_patch_dict(
                kind, json.loads(row[1]), patch, subresource=subresource,
                current_rv=row[0],
            )
            obj = self._load(kind, json.dumps(merged))
            rv = self._log(cur, MODIFIED, obj)
            obj.metadata.resource_version = rv
            cur.execute(
                "UPDATE log SET data=? WHERE rv=?", (self._dump(obj), rv)
            )
            cur.execute(
                "UPDATE objects SET rv=?, data=? "
                "WHERE kind=? AND namespace=? AND name=?",
                (rv, self._dump(obj), kind, namespace, name),
            )
        return obj

    def patch_batch(self, items: List[Dict[str, Any]]) -> List[Any]:
        """Per-item atomic patches in order, errors as values (the shared
        patch_batch contract; each item is its own transaction)."""
        return patch_batch_via_loop(self, items)

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        yield_point("store.delete", name)
        with self._txn("delete") as cur:
            row = cur.execute(
                "SELECT data FROM objects WHERE kind=? AND namespace=? AND name=?",
                (kind, namespace, name),
            ).fetchone()
            if row is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            obj = self._load(kind, row[0])
            cur.execute(
                "DELETE FROM objects WHERE kind=? AND namespace=? AND name=?",
                (kind, namespace, name),
            )
            # the DELETED log row allocates a fresh global rv; stamp it on the
            # object (kube does the same) so watch events carry strictly
            # increasing rvs — the anchor informer caches resume from
            rv = self._log(cur, DELETED, obj)
            obj.metadata.resource_version = rv
            cur.execute(
                "UPDATE log SET data=? WHERE rv=?", (self._dump(obj), rv)
            )
        return obj

    def try_delete(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except NotFound:
            return None

    # selector filtering is pushed into SQL (fully parameterized json_each —
    # label keys/values are data, never SQL) so a label-selected list of 8
    # pods in a 1600-pod cluster decodes 8 objects, not 1600: without this,
    # the server side of every `_list_workers` call was an O(cluster) JSON
    # decode — the exact load the informer cache exists to remove, paid
    # even by the residual non-cached callers (CLIs, cold caches)
    _SELECTOR_CLAUSE = (
        " AND EXISTS (SELECT 1 FROM"
        " json_each(COALESCE(json_extract(data, '$.metadata.labels'), '{}'))"
        " je WHERE je.key=? AND je.value=?)"
    )

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        q = "SELECT data FROM objects WHERE kind=?"
        args: list = [kind]
        if namespace is not None:
            q += " AND namespace=?"
            args.append(namespace)
        yield_point("store.list", kind)
        sql_selector = bool(selector) and self._json1
        if sql_selector:
            for k, v in selector.items():
                q += self._SELECTOR_CLAUSE
                args.extend((k, v))
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        out = []
        for (data,) in rows:
            obj = self._load(kind, data)
            if selector and not sql_selector:
                lbls = obj.metadata.labels
                if any(lbls.get(k) != v for k, v in selector.items()):
                    continue
            out.append(obj)
        out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return out

    def current_rv(self) -> int:
        """Global rv high-water mark (MAX over the log; the log keeps a
        retention floor so the newest rows are always present). Watch-resume
        anchor, same contract as ObjectStore.current_rv."""
        with self._lock:
            row = self._conn.execute("SELECT MAX(rv) FROM log").fetchone()
            if row[0]:
                return row[0]
            row = self._conn.execute("SELECT MAX(rv) FROM objects").fetchone()
            return row[0] or 0

    # -- watch ---------------------------------------------------------------

    def add_relist_listener(self, cb) -> None:
        """Register ``cb(objects)`` to be invoked (on the poll thread, in
        event order) whenever gap recovery relists. Informer caches need
        this: the relist's per-watcher MODIFIED stream cannot express
        deletions that happened inside the gap, so a cache must treat the
        relist as a full-state replacement — the callback hands it the
        complete live-object snapshot to do exactly that."""
        with self._lock:
            self._relist_listeners.append(cb)

    def watch(self, kind: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        with self._lock:
            self._watchers.append((kind, q))
            if self._poller is None:
                # watchers see only post-registration events (ObjectStore
                # semantics): skip log rows written before the first watch
                row = self._conn.execute("SELECT MAX(rv) FROM log").fetchone()
                self._last_seen_rv = row[0] or 0
                self._poller = threading.Thread(
                    target=self._poll_loop, name="sqlite-store-watch", daemon=True
                )
                self._poller.start()
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            self._watchers = [(k, w) for (k, w) in self._watchers if w is not q]

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                with self._lock:
                    rows = self._conn.execute(
                        "SELECT rv, etype, kind, data FROM log WHERE rv>? "
                        "ORDER BY rv",
                        (self._last_seen_rv,),
                    ).fetchall()
                    watchers = list(self._watchers)
                if (
                    rows
                    and self._last_seen_rv > 0
                    and rows[0][0] > self._last_seen_rv + 1
                ):
                    # rvs are contiguous AUTOINCREMENT: a gap means this
                    # poller stalled past cursor_stale_after and the rows it
                    # needed were trimmed (≙ a kube watch 'resourceVersion
                    # too old'). Recover by relisting: synthesize MODIFIED
                    # for every live object so level-triggered consumers
                    # reconverge. Boundary: DELETED events inside the gap
                    # are unrecoverable per-watcher (no per-watcher cache to
                    # diff) — controller reads self-heal, but an executor
                    # could keep a process for a pod deleted during a >60s
                    # stall.
                    self._relist_to(watchers)
                    # the relist already reflects these rows' effects; jump
                    # past them (replaying would emit stale versions AFTER
                    # the fresh relist state)
                    self._last_seen_rv = rows[-1][0]
                    rows = []
                if rows:
                    yield_point("store.watch-deliver", str(len(rows)))
                for rv, etype, kind, data in rows:
                    self._last_seen_rv = rv
                    try:
                        obj = self._load(kind, data)
                    except Exception:
                        log.debug("skipping undecodable %s row (newer "
                                  "writer version?)", kind, exc_info=True)
                        continue
                    origin, ts = self._origin_for(rv)
                    for want, wq in watchers:
                        if want is None or want == kind:
                            wq.put(WatchEvent(etype, kind, obj.deepcopy(),
                                              origin, ts))
                self._heartbeat_and_trim()
            except sqlite3.Error:
                pass  # transient lock contention; retry next tick
            self._stop.wait(self.poll_interval)

    def _relist_to(self, watchers) -> None:
        """Watch-gap recovery: emit a MODIFIED event per live object (the
        informer relist) to the given watchers, after handing relist
        listeners the full snapshot (they fire first so a cache's world-
        replacement precedes the redundant MODIFIED replay)."""
        with self._lock:
            rows = self._conn.execute("SELECT kind, data FROM objects").fetchall()
            listeners = list(self._relist_listeners)
        objs = []
        for kind, data in rows:
            try:
                objs.append(self._load(kind, data))
            except Exception:
                log.debug("skipping undecodable %s row in relist", kind,
                          exc_info=True)
                continue
        for cb in listeners:
            try:
                cb([o.deepcopy() for o in objs])
            except Exception:
                # a broken listener must not stall the watch pump — but a
                # silently dead informer is a debugging black hole (EXC001)
                log.exception("relist listener failed")
        for obj in objs:
            for want, wq in watchers:
                if want is None or want == obj.kind:
                    wq.put(WatchEvent(MODIFIED, obj.kind, obj.deepcopy()))

    # -- replication seam (machinery/replicated_store.py) --------------------
    #
    # The log table IS the replication WAL: every mutation's _txn commit
    # leaves one log row carrying the committed object at its rv, in
    # global commit order. A leader ships those rows verbatim; a follower
    # applies them at their EXACT rvs through apply_replicated, so leader
    # and follower stores are byte-for-byte the same history. Durable
    # election state (epoch) rides replica_meta via the same _txn seam.

    def log_tail(self, after_rv: int) -> List[Dict[str, Any]]:
        """Committed log rows with rv > ``after_rv``, in commit order —
        the shippable tail. Raises :class:`LogTruncated` when retention
        already trimmed rows the caller needs (the follower must resync
        from a snapshot instead; an incomplete tail silently shipped
        would be a gapped follower history)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT rv, etype, kind, data FROM log WHERE rv>? "
                "ORDER BY rv",
                (after_rv,),
            ).fetchall()
        if rows and rows[0][0] != after_rv + 1:
            raise LogTruncated(
                f"log tail after rv {after_rv} starts at {rows[0][0]} "
                f"(rows trimmed; snapshot transfer required)"
            )
        return [
            {"rv": rv, "etype": etype, "kind": kind, "data": data}
            for (rv, etype, kind, data) in rows
        ]

    def tail_hash(self, rv: int) -> Optional[str]:
        """Content fingerprint of the log row at ``rv`` (None when absent
        or rv <= 0). Shipping carries the sender's hash of the entry
        preceding the tail; a mismatch on the receiver is DIVERGENCE — a
        same-rv row from a dead epoch (an unacked suffix) that must be
        truncated by snapshot resync, which a bare rv compare can never
        see."""
        if rv <= 0:
            return None
        with self._lock:
            row = self._conn.execute(
                "SELECT etype, kind, data FROM log WHERE rv=?", (rv,)
            ).fetchone()
        if row is None:
            return None
        return entry_hash({"rv": rv, "etype": row[0], "kind": row[1],
                           "data": row[2]})

    def apply_replicated(self, entries: List[Dict[str, Any]]) -> int:
        """THE follower write path: apply shipped log entries at their
        exact rvs, atomically as one transaction (a crash mid-batch loses
        the whole batch; the leader re-ships — a partially applied batch
        would be a history no leader ever committed). The watch poller
        picks the new rows up like any local commit, so follower watch
        fan-out needs no extra plumbing. Returns the new applied rv."""
        if not entries:
            return self.current_rv()
        with self._txn("replicate") as cur:
            for e in entries:
                cur.execute(
                    "INSERT INTO log (rv, etype, kind, data) "
                    "VALUES (?, ?, ?, ?)",
                    (e["rv"], e["etype"], e["kind"], e["data"]),
                )
                obj = json.loads(e["data"])
                m = obj.get("metadata") or {}
                if e["etype"] == DELETED:
                    cur.execute(
                        "DELETE FROM objects WHERE kind=? AND namespace=? "
                        "AND name=?",
                        (e["kind"], m.get("namespace"), m.get("name")),
                    )
                else:
                    cur.execute(
                        "INSERT OR REPLACE INTO objects "
                        "(kind, namespace, name, rv, data) "
                        "VALUES (?, ?, ?, ?, ?)",
                        (e["kind"], m.get("namespace"), m.get("name"),
                         e["rv"], e["data"]),
                    )
        return self.current_rv()

    def snapshot_state(self, log_rows: int = 256) -> Dict[str, Any]:
        """Full-state transfer payload for follower resync: every live
        object row plus the newest ``log_rows`` log rows (enough tail for
        the receiver to serve hash checks and watch resumes afterwards)."""
        with self._lock:
            objects = self._conn.execute(
                "SELECT kind, namespace, name, rv, data FROM objects"
            ).fetchall()
            tail = self._conn.execute(
                "SELECT rv, etype, kind, data FROM log "
                "ORDER BY rv DESC LIMIT ?",
                (log_rows,),
            ).fetchall()
        return {
            "rv": self.current_rv(),
            "objects": [list(r) for r in objects],
            "log": [list(r) for r in sorted(tail)],
        }

    def load_snapshot(self, snap: Dict[str, Any]) -> int:
        """Replace this store's history with a snapshot (divergent-suffix
        truncation + lag catch-up in one move). The log's AUTOINCREMENT
        sequence is CLAMPED to the snapshot head: left alone, a wiped
        suffix whose rvs were numerically higher would make this node's
        next local commit skip rv numbers — a permanent gap its own
        ``log_tail`` would then reject as truncated, wedging every write
        the moment it becomes leader. Re-numbering over the wiped suffix
        is exactly right: the new history REPLACED those rvs. Watchers
        are force-relisted afterwards: their per-event stream cannot
        express a history swap, the full-state replacement can."""
        head = int(snap.get("rv", 0))
        with self._txn("load-snapshot") as cur:
            cur.execute("DELETE FROM objects")
            cur.execute("DELETE FROM log")
            for kind, ns, name, rv, data in snap.get("objects", ()):
                cur.execute(
                    "INSERT INTO objects (kind, namespace, name, rv, data) "
                    "VALUES (?, ?, ?, ?, ?)",
                    (kind, ns, name, rv, data),
                )
            for rv, etype, kind, data in snap.get("log", ()):
                cur.execute(
                    "INSERT INTO log (rv, etype, kind, data) "
                    "VALUES (?, ?, ?, ?)",
                    (rv, etype, kind, data),
                )
            cur.execute(
                "UPDATE sqlite_sequence SET seq=? WHERE name='log'",
                (head,),
            )
        self.force_relist()
        return self.current_rv()

    def force_relist(self) -> None:
        """Re-deliver the full live state to every watcher as a relist
        (listener world-replacement + MODIFIED replay) and park the poll
        cursor at the new head — the recovery event after load_snapshot
        rewrote history out from under the per-row watch stream."""
        with self._lock:
            watchers = list(self._watchers)
            row = self._conn.execute("SELECT MAX(rv) FROM log").fetchone()
            self._last_seen_rv = row[0] or 0
        self._relist_to(watchers)

    def get_meta(self, key: str, default: Optional[str] = None
                 ) -> Optional[str]:
        """Durable replica metadata (election epoch). Reads are plain
        SELECTs; writes ride set_meta's _txn."""
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM replica_meta WHERE key=?", (key,)
            ).fetchone()
        return default if row is None else row[0]

    def set_meta(self, key: str, value: str) -> None:
        with self._txn("meta") as cur:
            cur.execute(
                "INSERT INTO replica_meta (key, value) VALUES (?, ?) "
                "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
                (key, value),
            )

    # -- log retention -------------------------------------------------------

    _TRIM_EVERY = 5.0  # seconds between retention passes

    def _heartbeat_and_trim(self) -> None:
        """Advertise this process's watch progress and trim log rows every
        live watcher has consumed (see __init__ docstring)."""
        now = time.time()
        if now - self._last_trim < self._TRIM_EVERY:
            return
        self._last_trim = now
        with self._txn("trim") as cur:
            cur.execute(
                "INSERT INTO watch_cursors (id, last_rv, updated) "
                "VALUES (?, ?, ?) ON CONFLICT(id) DO UPDATE SET "
                "last_rv=excluded.last_rv, updated=excluded.updated",
                (self._cursor_id, self._last_seen_rv, now),
            )
            live = cur.execute(
                "SELECT MIN(last_rv) FROM watch_cursors WHERE updated > ?",
                (now - self.cursor_stale_after,),
            ).fetchone()[0]
            cur.execute(
                "DELETE FROM watch_cursors WHERE updated <= ?",
                (now - self.cursor_stale_after,),
            )
            max_rv = cur.execute("SELECT MAX(rv) FROM log").fetchone()[0] or 0
            # keep the retention floor AND anything an active watcher still
            # needs — whichever bound is lower wins
            horizon = max_rv - self.log_retention_rows
            if live is not None:
                horizon = min(horizon, live)
            if horizon > 0:
                cur.execute("DELETE FROM log WHERE rv <= ?", (horizon,))

    def close(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=2.0)
        with self._lock:
            try:
                with self._txn("close") as cur:
                    cur.execute(
                        "DELETE FROM watch_cursors WHERE id=?",
                        (self._cursor_id,),
                    )
            except sqlite3.Error:
                pass  # closing is best-effort; stale expiry reclaims it
            self._conn.close()
