"""Informer/lister cache: watch-fed local reads for the control plane.

≙ client-go's SharedInformer + indexed Lister pair, the machinery the whole
reference control plane reads through (informer wiring in
NewMPIJobController, v2/pkg/controller/mpi_job_controller.go:248-341;
syncHandler reads listers, never the apiserver, :443-608). Before this
module, every reconcile issued full ``store.list`` round-trips — over HTTP
in the distributed deployment — so store load scaled as
O(jobs × pods × resyncs). With it:

- **One watch feeds everything.** The cache registers a single
  ``store.watch(None)``, snapshots every kind with an initial LIST, then
  applies events forever. Components read via :meth:`InformerCache.get` /
  ``list`` — the same duck-typed read surface as a store — and the steady-
  state store traffic drops to writes plus one long-poll.
- **Label indices.** Kinds are indexed by configured label keys (by default
  ``tpujob.dev/job-name``), so "this job's workers" is a dict hit, not a
  scan over every pod in the cluster (≙ the namespace/label indexers every
  client-go lister is built on).
- **has_synced gating.** Reads before the initial snapshot completes would
  observe an empty world and make eager decisions (delete "missing"
  dependents, admit gangs against phantom-free capacity); consumers gate on
  :meth:`has_synced` exactly like client-go's WaitForCacheSync.
- **Resync correctness.** Events are applied under a resource_version guard
  (strictly increasing per object now that deletes also bump rv), so the
  LIST-vs-watch interleave can never regress the cache. When a backend has
  to relist after a watch gap (SqliteStore poll stall, http server restart
  past the event ring), the per-object MODIFIED replay cannot express
  deletions — so the cache registers a relist listener
  (``add_relist_listener``) and REPLACES its world from the snapshot,
  closing the deleted-object leak.

Writes never go through the cache: components keep writing to the store and
observe their own updates through the watch, exactly like client-go.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import time

from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.store import (
    ADDED,
    DELETED,
    MODIFIED,
    NotFound,
    WatchEvent,
)
from mpi_operator_tpu.machinery.yieldpoints import yield_point
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.cache")

# the one label every control-plane lookup keys on (duplicated from
# controller/controller.py so machinery stays import-light; the controller
# tests assert the two never drift)
LABEL_JOB_NAME = "tpujob.dev/job-name"
LABEL_SERVE_NAME = "tpujob.dev/serve-name"

# default kind set mirrors machinery.objects.KINDS minus Event: events are
# an append-only audit stream nobody ever gets/lists on the hot path, and
# caching them would grow the cache without bound. Alerts (the SLO plane's
# firing state, one object per objective) ARE cached: consumers watch for
# transitions and `ctl top` reads them as a lister would
DEFAULT_KINDS = ("TPUJob", "TPUServe", "Alert", "Pod", "Service",
                 "ConfigMap", "PodGroup", "Node")


class _Relist:
    """Queue marker carrying a full live-object snapshot (watch-gap
    recovery): the drain loop replaces the cached world with it."""

    def __init__(self, objects: List[Any]):
        self.objects = objects


def _rv(obj: Any) -> int:
    return obj.metadata.resource_version or 0


class Lister:
    """Read-only, thread-safe view over one kind. Objects are deep-copied on
    the way out — the informer-cache rule ("read-only + DeepCopy before
    mutation", SURVEY.md §5.2) enforced mechanically, because controller
    code mutates what it reads."""

    def __init__(self, kind: str, index_labels: Tuple[str, ...] = ()):
        self.kind = kind
        self._lock = threading.RLock()
        self._objects: Dict[Tuple[str, str], Any] = {}  # (ns, name) → obj
        # label key → label value → {(ns, name)}
        self._index_labels = tuple(index_labels)
        self._index: Dict[str, Dict[str, set]] = {
            k: {} for k in self._index_labels
        }

    # -- mutation (informer thread only) ------------------------------------

    def _unindex(self, key: Tuple[str, str], obj: Any) -> None:
        for lk in self._index_labels:
            lv = obj.metadata.labels.get(lk)
            if lv is None:
                continue
            bucket = self._index[lk].get(lv)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._index[lk][lv]

    def _reindex(self, key: Tuple[str, str], obj: Any) -> None:
        for lk in self._index_labels:
            lv = obj.metadata.labels.get(lk)
            if lv is not None:
                self._index[lk].setdefault(lv, set()).add(key)

    def apply(self, etype: str, obj: Any) -> None:
        """Apply one watch event under the rv guard: a stale event (queued
        before a fresher LIST/relist merged) can never regress the cache."""
        key = (obj.metadata.namespace, obj.metadata.name)
        yield_point("cache.apply", etype)
        with self._lock:
            cur = self._objects.get(key)
            if cur is not None and _rv(obj) < _rv(cur):
                return  # stale replay
            if etype == DELETED:
                if cur is not None:
                    self._unindex(key, cur)
                    del self._objects[key]
                return
            if cur is not None:
                self._unindex(key, cur)
            self._objects[key] = obj
            self._reindex(key, obj)

    def merge(self, objects: List[Any]) -> None:
        """Merge an initial LIST snapshot: upsert under the rv guard without
        deleting — events already applied may be fresher than the snapshot,
        never the other way around."""
        with self._lock:
            for obj in objects:
                self.apply(MODIFIED, obj)

    def replace(self, objects: List[Any]) -> None:
        """Full-state replacement (watch-gap relist): anything absent from
        the snapshot was deleted inside the gap and is dropped — the leak a
        MODIFIED-only replay cannot close. Present objects still merge under
        the rv guard (an event that raced ahead of the snapshot wins)."""
        with self._lock:
            keep = {(o.metadata.namespace, o.metadata.name) for o in objects}
            for key in [k for k in self._objects if k not in keep]:
                self._unindex(key, self._objects[key])
                del self._objects[key]
            for obj in objects:
                self.apply(MODIFIED, obj)

    # -- reads ---------------------------------------------------------------

    def get(self, namespace: str, name: str) -> Any:
        yield_point("cache.get", name)
        with self._lock:
            obj = self._objects.get((namespace, name))
            if obj is None:
                raise NotFound(f"{self.kind} {namespace}/{name} not found")
            return obj.deepcopy()

    def try_get(self, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(namespace, name)
        except NotFound:
            return None

    def by_label(self, label_key: str, label_value: str) -> List[Any]:
        """Indexed lookup: every cached object carrying label_key=label_value
        (label_key must be one of the configured index labels)."""
        with self._lock:
            keys = self._index[label_key].get(label_value, ())
            out = [self._objects[k].deepcopy() for k in keys]
        out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return out

    def list(
        self,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
        copy: bool = True,
    ) -> List[Any]:
        """Same contract (and sort order) as ``store.list(kind, ...)``. When
        the selector carries an indexed label the candidate set is a dict
        hit; the remaining selector pairs and the namespace filter apply on
        top.

        ``copy=False`` returns the CACHED objects themselves — strictly
        read-only for the caller, invalidated by the next watch apply (the
        10k-job round: the gang scheduler's per-pass Node list deepcopied
        1k Nodes 5×/second; a read-only snapshot is free). Callers that
        mutate or retain the result must keep the default."""
        yield_point("cache.list", self.kind)
        with self._lock:
            candidates = None
            if selector:
                for lk in self._index_labels:
                    if lk in selector:
                        keys = self._index[lk].get(selector[lk], ())
                        candidates = [self._objects[k] for k in keys]
                        break
            if candidates is None:
                candidates = self._objects.values()
            out = []
            for obj in candidates:
                m = obj.metadata
                if namespace is not None and m.namespace != namespace:
                    continue
                if selector and any(
                    m.labels.get(sk) != sv for sk, sv in selector.items()
                ):
                    continue
                out.append(obj.deepcopy() if copy else obj)
        out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)


class InformerCache:
    """Watch-fed cache over every control-plane kind, exposing the store's
    read surface (get/try_get/list) plus per-kind indexed listers.

    Lifecycle: ``start()`` registers the watch, then a background thread
    takes the initial LIST snapshot, flips :meth:`has_synced`, and applies
    events until ``stop()``. Consumers that would act on an empty world
    must gate on ``has_synced()`` / ``wait_for_sync()`` (≙ client-go's
    WaitForCacheSync before starting workers).
    """

    def __init__(
        self,
        store: Any,
        kinds: Tuple[str, ...] = DEFAULT_KINDS,
        # both workload classes' gang-grouping labels are indexed: the
        # serve controller's and autoscaler's per-serve pod lists must be
        # index hits, not O(all cached pods) scans per tick
        index_labels: Tuple[str, ...] = (LABEL_JOB_NAME, LABEL_SERVE_NAME),
    ):
        self.store = store
        self.kinds = tuple(kinds)
        self._listers: Dict[str, Lister] = {
            k: Lister(k, index_labels) for k in self.kinds
        }
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._q = None
        self._thread: Optional[threading.Thread] = None
        self._handlers_lock = threading.Lock()
        self._handlers: List = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "InformerCache":
        """Register the watch (and the relist listener, when the backend can
        gap) BEFORE listing: events raced between watch registration and the
        LIST are queued and merge under the rv guard, so nothing is missed —
        the list-then-watch ordering a kube Reflector needs its
        resourceVersion anchor for, inverted to fit this watch contract."""
        if self._thread is not None:
            return self
        self._q = self.store.watch(None)
        add_listener = getattr(self.store, "add_relist_listener", None)
        if callable(add_listener):
            add_listener(self._on_relist)
        self._thread = threading.Thread(
            target=self._run, name="informer-cache", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._q is not None:
            self.store.stop_watch(self._q)
            self._q.put(None)  # wake the drain

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_for_sync(self, timeout: Optional[float] = None) -> bool:
        return self._synced.wait(timeout)

    def add_event_handler(self, cb) -> None:
        """Register ``cb(etype, obj)``, invoked on the informer thread AFTER
        each event is applied to its lister (relists fire MODIFIED per
        surviving object). THE workqueue coupling of client-go: a consumer
        that enqueues work from this callback is guaranteed the cache
        already reflects the event when the work is processed — an enqueue
        fed by a separate direct store watch can race ahead of the cache,
        read a miss, and drop the key forever."""
        with self._handlers_lock:
            self._handlers.append(cb)

    def _fire(self, etype: str, obj: Any) -> None:
        with self._handlers_lock:
            handlers = list(self._handlers)
        for cb in handlers:
            try:
                cb(etype, obj)
            except Exception:
                log.exception("informer event handler failed")

    # -- pump ----------------------------------------------------------------

    def _on_relist(self, objects: List[Any]) -> None:
        """Relist listener (store poll thread): enqueue the snapshot as a
        marker IN EVENT ORDER — the drain loop replaces the world when it
        reaches it, so deletions inside the gap are dropped."""
        if self._q is not None:
            self._q.put(_Relist(objects))

    def _initial_sync(self) -> None:
        for kind in self.kinds:
            if self._stop.is_set():
                return
            while not self._stop.is_set():
                try:
                    self._listers[kind].merge(self.store.list(kind))
                    break
                except Exception:
                    # store briefly unreachable at startup: informer
                    # backoff-and-retry; has_synced stays False so gated
                    # consumers keep waiting
                    log.warning("initial list of %s failed; retrying", kind,
                                exc_info=True)
                    if self._stop.wait(0.5):
                        return
        self._synced.set()
        for kind in self.kinds:
            metrics.informer_objects.set(len(self._listers[kind]), kind=kind)
        metrics.informer_synced.set(1)

    def _run(self) -> None:
        self._initial_sync()
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None:
                continue  # stop() wake-up
            if isinstance(item, _Relist):
                by_kind: Dict[str, List[Any]] = {k: [] for k in self.kinds}
                for obj in item.objects:
                    if obj.kind in by_kind:
                        by_kind[obj.kind].append(obj)
                for kind, objs in by_kind.items():
                    self._listers[kind].replace(objs)
                    metrics.informer_objects.set(
                        len(self._listers[kind]), kind=kind)
                for objs in by_kind.values():
                    for obj in objs:
                        self._fire(MODIFIED, obj)
                continue
            ev: WatchEvent = item
            lister = self._listers.get(ev.kind)
            if lister is not None and ev.type in (ADDED, MODIFIED, DELETED):
                lister.apply(ev.type, ev.obj)
                metrics.informer_objects.set(len(lister), kind=ev.kind)
                ts = getattr(ev, "ts", 0.0)
                if ts:
                    # commit-to-delivery lag: how stale a lister read can
                    # be (clamped — a skewed remote clock must not observe
                    # a negative latency)
                    metrics.watch_delivery_lag.observe(
                        max(0.0, time.time() - ts)
                    )
                # expose the originating write's span to the handlers
                # (controller enqueue, scheduler wake) for the duration of
                # this delivery: the work the event causes parents on it
                trace.set_delivery(getattr(ev, "trace", None))
                try:
                    self._fire(ev.type, ev.obj)
                finally:
                    trace.clear_delivery()

    # -- read surface (duck-typed like a store, reads only) ------------------

    def lister(self, kind: str) -> Lister:
        return self._listers[kind]

    def get(self, kind: str, namespace: str, name: str) -> Any:
        return self._listers[kind].get(namespace, name)

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        return self._listers[kind].try_get(namespace, name)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
        copy: bool = True,
    ) -> List[Any]:
        return self._listers[kind].list(namespace, selector, copy=copy)
