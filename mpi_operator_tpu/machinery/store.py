"""Thread-safe versioned object store with watches.

≙ the kube-apiserver + informer-cache layer the reference depends on. The
semantics preserved from the reference's usage:

- **resourceVersion optimistic concurrency**: updates with a stale
  resource_version raise Conflict (the reference relies on apiserver conflicts
  + requeue; our controller does the same).
- **Watches**: every create/update/delete fans out a WatchEvent to subscriber
  queues, which is what informers consume (≙ the event handlers registered in
  NewMPIJobController, v2/pkg/controller/mpi_job_controller.go:300-339).
- **Objects are deep-copied on the way in and out** so callers can never
  mutate the store's copy — the same rule as informer caches ("read-only +
  DeepCopy before mutation", SURVEY.md §5.2).
- **Label selection** for list operations (≙ the group/job-name selector the
  controller lists pods with, :689-707).
"""

from __future__ import annotations

import logging
import queue
import threading
import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from mpi_operator_tpu.machinery import trace as _trace
from mpi_operator_tpu.machinery.yieldpoints import yield_point


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class Conflict(RuntimeError):
    pass


class Unauthorized(PermissionError):
    """A store request was rejected for a missing/wrong bearer token
    (HTTP backend only; ≙ kube-apiserver authn rejecting a client,
    /root/reference/manifests/base/cluster-role.yaml being the authz side)."""


class Forbidden(PermissionError):
    """A store request authenticated with the READ-ONLY token tried to
    mutate (HTTP backend only; ≙ the aggregated view-vs-edit ClusterRole
    split of /root/reference/manifests/base/cluster-role.yaml:96-151 —
    a viewer physically cannot delete a job)."""


class BadPatch(ValueError):
    """A merge-patch was malformed or tried to cross a boundary the patch
    surface freezes (identity metadata; anything but status through the
    status subresource). 400 on the HTTP seam — a caller bug, never a
    retryable condition."""


class TooManyRequests(RuntimeError):
    """The store's fair-queuing admission rejected this request: the
    caller's tenant is over its rate limit or its bounded wait queue is
    full (machinery/fairqueue.py — the APF posture: load-shed the noisy
    tenant instead of letting it starve everyone else). 429 on the wire.
    DEFINITE: nothing was committed; retry after backing off."""


class QuotaExceeded(Forbidden):
    """A create was rejected by namespace quota admission (max jobs /
    max chips per namespace — the reference's ResourceQuota layer,
    PAPER.md §1). A policy denial, not a transient: 403 on the wire,
    and retrying without freeing capacity will keep failing."""


class NotLeader(RuntimeError):
    """A mutation reached a replica that is not the leased leader
    (machinery/replicated_store.py). DEFINITE: nothing was staged or
    committed anywhere, so callers retry against the leader freely.
    ``leader`` carries the rejecting replica's best leader hint (an
    advertised URL on the HTTP seam, a node id in-process) — 421 on the
    wire, and HttpStoreClient follows the hint before backing off."""

    def __init__(self, message: str, *, leader: Optional[str] = None):
        super().__init__(message)
        self.leader = leader


class ReplicationUnavailable(RuntimeError):
    """The leader could not confirm a majority durably applied a write it
    already committed locally — the INDETERMINATE outcome class (≙ a kube
    apiserver timeout): the write may surface later (it is durable on a
    minority) or never (a new leader's history may truncate it). Callers
    must re-read before retrying non-idempotent verbs; blind retry of a
    create can legally land AlreadyExists."""


ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

# Metadata fields a merge-patch may never change: they ARE the object's
# identity (the store key + the incarnation guard every optimistic consumer
# leans on). resource_version is excluded — submitting it is the documented
# precondition mechanism, and the store restamps it anyway.
_IDENTITY_META = ("name", "namespace", "uid", "creation_timestamp")


def json_merge_patch(target: Any, patch: Any) -> Any:
    """RFC 7386 JSON merge-patch: maps merge recursively, ``null`` deletes
    the key, everything else (lists included) replaces wholesale."""
    if not isinstance(patch, dict):
        return patch
    out = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = json_merge_patch(out.get(k), v)
    return out


_MISSING = object()


def diff_merge_patch(old: Any, new: Any) -> Dict[str, Any]:
    """The minimal RFC 7386 patch transforming ``old`` into ``new`` (both
    plain dicts): unchanged keys are omitted, removed keys become ``null``.
    THE way write paths build their patches — sending the full intended
    object as a merge-patch could never *delete* a stale key, and sending
    only hand-picked fields forgets the deletions too."""
    patch: Dict[str, Any] = {}
    old = old if isinstance(old, dict) else {}
    for k, v in new.items():
        ov = old.get(k, _MISSING)
        if isinstance(v, dict) and isinstance(ov, dict):
            sub = diff_merge_patch(ov, v)
            if sub:
                patch[k] = sub
        elif ov is _MISSING or ov != v:
            patch[k] = v
    for k in old:
        if k not in new:
            patch[k] = None
    return patch


def apply_merge_patch_dict(
    kind: str,
    current: Dict[str, Any],
    patch: Any,
    *,
    subresource: Optional[str] = None,
    current_rv: Optional[int] = None,
) -> Dict[str, Any]:
    """Validate + apply a merge-patch to an encoded object dict — THE shared
    core of every backend's ``patch`` verb, so the three stores can never
    drift on semantics. Enforces, atomically with the merge:

    - **rv precondition**: a ``metadata.resource_version`` in the patch must
      match ``current_rv`` or the write raises Conflict (the optimistic
      hook for writers that must not build on a state they haven't seen —
      e.g. the scheduler's binding). Omitting it applies the patch to
      whatever is latest: status mirrors want exactly that.
    - **identity freeze**: name/namespace/uid/creation_timestamp and kind
      are immutable through ANY patch (they are the store key and the
      incarnation guard).
    - **status subresource**: ``subresource='status'`` may touch only
      ``status`` (plus the rv precondition) — spec and metadata are frozen
      server-side, which is what lets the NODE token tier be granted
      patch-status-only on its pods (≙ the kube /status subresource).

    Returns the merged dict; the caller stamps the fresh resource_version
    and persists under its own lock.
    """
    if not isinstance(patch, dict):
        raise BadPatch(
            f"merge patch must be a JSON object, got {type(patch).__name__}"
        )
    meta_patch = patch.get("metadata")
    if meta_patch is not None and not isinstance(meta_patch, dict):
        raise BadPatch("metadata patch must be a JSON object")
    expected = (meta_patch or {}).get("resource_version")
    if expected is not None and current_rv is not None and expected != current_rv:
        raise Conflict(
            f"{kind}: resource_version {expected} != {current_rv}"
        )
    # uid PRECONDITION (≙ kube's metadata.uid preconditions): the write
    # applies only to this exact incarnation. Checked atomically with the
    # merge, which is what lets an authorizer PIN the object it inspected —
    # the agent tier's apply-time scope enforcement rides this (a pod
    # deleted and recreated between authz and apply can never be hit).
    expected_uid = (meta_patch or {}).get("uid")
    cur_uid = (current.get("metadata") or {}).get("uid")
    if expected_uid is not None and expected_uid != cur_uid:
        raise Conflict(f"{kind}: uid {expected_uid!r} != {cur_uid!r}")
    if subresource is not None and subresource != "status":
        raise BadPatch(f"unknown subresource {subresource!r}")
    if subresource == "status":
        for key in patch:
            if key not in ("status", "metadata"):
                raise BadPatch(
                    f"status subresource cannot modify {key!r} "
                    f"(spec/metadata are frozen)"
                )
        frozen = set(meta_patch or ()) - {"resource_version", "uid"}
        if frozen:
            raise BadPatch(
                f"status subresource cannot modify "
                f"metadata.{sorted(frozen)[0]} (spec/metadata are frozen)"
            )
        status_patch = patch.get("status")
        if status_patch is not None and not isinstance(status_patch, dict):
            raise BadPatch("status patch must be a JSON object")
        out = dict(current)
        merged_status = json_merge_patch(
            current.get("status", {}), status_patch or {}
        )
        if merged_status:
            out["status"] = merged_status
        else:
            out.pop("status", None)
        return out
    out = json_merge_patch(current, patch)
    if out.get("kind", kind) != current.get("kind", kind):
        raise BadPatch(f"patch may not change kind {current.get('kind')!r}")
    cur_meta = current.get("metadata", {})
    new_meta = out.get("metadata", {})
    for f in _IDENTITY_META:
        if new_meta.get(f) != cur_meta.get(f):
            raise BadPatch(
                f"patch may not change metadata.{f} "
                f"({cur_meta.get(f)!r} -> {new_meta.get(f)!r})"
            )
    return out


# error classes a single batch item may resolve to without failing the
# whole batch (everything else — store down, bad wire shape — is the
# request's problem, not the item's)
PATCH_ITEM_ERRORS = (NotFound, Conflict, BadPatch)


def patch_batch_via_loop(store, items: List[Dict[str, Any]]) -> List[Any]:
    """Default ``patch_batch``: apply each item's patch in order, mapping
    per-item failures to exception VALUES (not raises) so one bad item
    can't hide the others' results. Each item is atomic on its own; the
    batch deliberately is not a transaction — it exists to collapse
    round-trips (the HTTP backend ships it as one request), not to couple
    unrelated objects' fates.

    The partial-failure contract, pinned across all three backends by
    tests/test_patch.py and the differential fuzzer (the
    ``batch-aborts-on-error`` seeded mutant proves a deviation is caught):

    - **per-item results**: ``out[i]`` is item i's committed object or its
      store error VALUE; ``len(out) == len(items)`` always — a mid-batch
      error never swallows the suffix (one dead pod's mirror must not take
      the heartbeat riding behind it down);
    - **applied-prefix visibility**: items commit strictly in list order,
      each visible to readers (and to later items in the SAME batch —
      item j sees item i<j's rv bump) the moment it lands; a failed item
      rolls back nothing;
    - **watch ordering**: exactly the successful items emit MODIFIED
      events, in list order, carrying strictly increasing rvs; failed
      items emit nothing."""
    out: List[Any] = []
    for it in items:
        try:
            if not isinstance(it, dict):
                raise BadPatch("batch item must be an object")
            out.append(
                store.patch(
                    it["kind"], it["namespace"], it["name"], it.get("patch"),
                    subresource=it.get("subresource"),
                )
            )
        except PATCH_ITEM_ERRORS as e:
            out.append(e)
        except KeyError as e:  # a missing kind/namespace/name key
            out.append(BadPatch(f"batch item missing {e}"))
    return out


def optimistic_update(store, kind, namespace, name, mutate, *,
                      attempts: int = 5, what: str = "update"):
    """get → ``mutate(copy)`` → non-force update, re-reading on Conflict.

    THE write pattern for fields shared between writers (eviction vs the
    reaper, cordon vs the heartbeat, unbind vs an executor launch): a forced
    write would clobber whichever concurrent transition lands first; this
    re-reads and re-checks instead. ``mutate(cur)`` edits the freshly-read
    object in place and returns True to proceed (False aborts — the
    precondition no longer holds on the current copy). Returns the committed
    object, or None when the object is missing, the precondition failed, or
    every attempt lost the race — exhaustion is logged, because callers are
    often one-shot (``ctl drain``, agent restart reconciliation) and would
    otherwise silently skip a live object."""
    for _ in range(attempts):
        try:
            cur = store.get(kind, namespace, name)
        except KeyError:  # NotFound subclasses KeyError
            return None
        if not mutate(cur):
            return None
        try:
            # oplint: disable=RMW001 — this helper IS the sanctioned
            # read-modify-write: the one conflict-retried GET+PUT the rule
            # points callers at when a merge-patch cannot express the write
            # (multi-field transitions with read-side preconditions)
            return store.update(cur)
        except KeyError:
            return None
        except Conflict:
            continue
    logging.getLogger("tpujob.machinery").warning(
        "%s: optimistic update of %s %s/%s lost the write race %dx; left as-is",
        what, kind, namespace, name, attempts,
    )
    return None


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    obj: Any
    # causal origin of the write that produced this event: a plain
    # (trace_id, span_id) tuple (or None when the writer was untraced) —
    # consumers parent the work the event causes on it (machinery/trace.py
    # set_delivery/get_delivery), which is what lets `ctl trace` link a
    # reconcile back to the write that triggered it
    trace: Any = None
    # commit timestamp (0.0 = unknown): the informer cache observes
    # now - ts as the watch delivery lag histogram
    ts: float = 0.0


def _meta(obj: Any):
    return obj.metadata


class ObjectStore:
    """In-process apiserver equivalent. Keyed by (kind, namespace, name)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[Tuple[str, str, str], Any] = {}
        self._rv = 0
        self._watchers: List[Tuple[Optional[str], "queue.Queue[WatchEvent]"]] = []
        self._now = __import__("time").time

    # -- internal ----------------------------------------------------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _notify(self, etype: str, kind: str, obj: Any) -> None:
        yield_point("store.watch-deliver", kind)
        # stamp the writing span's context (and the commit time) onto the
        # event so consumers can link the work it triggers back to this
        # write; current_ids() is None-cheap when tracing is off
        origin = _trace.current_ids()
        ts = self._now()
        for want_kind, q in list(self._watchers):
            if want_kind is None or want_kind == kind:
                q.put(WatchEvent(etype, kind, obj.deepcopy(), origin, ts))

    @staticmethod
    def _key(kind: str, namespace: str, name: str) -> Tuple[str, str, str]:
        return (kind, namespace, name)

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        yield_point("store.create", obj.kind)
        with self._lock:
            m = _meta(obj)
            k = self._key(obj.kind, m.namespace, m.name)
            if k in self._objects:
                raise AlreadyExists(f"{obj.kind} {m.namespace}/{m.name} already exists")
            obj = obj.deepcopy()
            m = _meta(obj)
            if not m.uid:
                m.uid = str(uuid.uuid4())
            m.resource_version = self._next_rv()
            if m.creation_timestamp is None:
                m.creation_timestamp = self._now()
            self._objects[k] = obj
            self._notify(ADDED, obj.kind, obj)
            return obj.deepcopy()

    def get(self, kind: str, namespace: str, name: str) -> Any:
        yield_point("store.get", name)
        with self._lock:
            k = self._key(kind, namespace, name)
            if k not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return self._objects[k].deepcopy()

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def update(self, obj: Any, force: bool = False) -> Any:
        """Optimistic update; ``force=True`` skips the resource_version check
        (used by test fixtures playing kubelet, ≙ envtest's updatePodsToPhase,
        v2/test/integration/mpi_job_controller_test.go)."""
        yield_point("store.put", obj.kind)
        with self._lock:
            m = _meta(obj)
            k = self._key(obj.kind, m.namespace, m.name)
            if k not in self._objects:
                raise NotFound(f"{obj.kind} {m.namespace}/{m.name} not found")
            current = self._objects[k]
            if not force and m.resource_version != _meta(current).resource_version:
                raise Conflict(
                    f"{obj.kind} {m.namespace}/{m.name}: resource_version "
                    f"{m.resource_version} != {_meta(current).resource_version}"
                )
            obj = obj.deepcopy()
            _meta(obj).resource_version = self._next_rv()
            self._objects[k] = obj
            self._notify(MODIFIED, obj.kind, obj)
            return obj.deepcopy()

    def patch(
        self,
        kind: str,
        namespace: str,
        name: str,
        patch: Any,
        *,
        subresource: Optional[str] = None,
    ) -> Any:
        """Apply a JSON merge-patch atomically under the store lock: one
        round-trip replaces the whole GET+PUT+409-retry loop for writers
        that only touch fields they own (status mirrors, heartbeats,
        bindings). Semantics — rv precondition, identity freeze, the
        status subresource — live in :func:`apply_merge_patch_dict`;
        the commit bumps resource_version and emits MODIFIED like any
        update."""
        from mpi_operator_tpu.machinery.serialize import decode, encode

        yield_point("store.patch", name)
        with self._lock:
            k = self._key(kind, namespace, name)
            if k not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            current = self._objects[k]
            merged = apply_merge_patch_dict(
                kind, encode(current), patch, subresource=subresource,
                current_rv=_meta(current).resource_version,
            )
            obj = decode(kind, merged)
            _meta(obj).resource_version = self._next_rv()
            self._objects[k] = obj
            self._notify(MODIFIED, kind, obj)
            return obj.deepcopy()

    def patch_batch(self, items: List[Dict[str, Any]]) -> List[Any]:
        """Apply a list of ``{kind, namespace, name, patch[, subresource]}``
        items in order; per-item errors come back as exception values (see
        patch_batch_via_loop). In-process this is just a loop — the verb
        exists so agents batching a heartbeat + pod mirrors run unchanged
        against every backend, and the HTTP backend collapses it to ONE
        request."""
        return patch_batch_via_loop(self, items)

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        yield_point("store.delete", name)
        with self._lock:
            k = self._key(kind, namespace, name)
            if k not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            obj = self._objects.pop(k)
            # deletion consumes a resource_version (kube does the same): every
            # watch event then carries a strictly increasing rv, which is what
            # the informer cache and the http watch ?resource_version= resume
            # anchor on — a DELETED event sharing the rv of the preceding
            # MODIFIED would be skippable on resume (a lost deletion)
            _meta(obj).resource_version = self._next_rv()
            self._notify(DELETED, kind, obj)
            return obj.deepcopy()

    def try_delete(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except NotFound:
            return None

    def current_rv(self) -> int:
        """The store's resource_version high-water mark. Watch-resume anchor:
        a consumer that has observed every event up to ``current_rv()`` holds
        a complete picture (≙ the list resourceVersion a kube Reflector
        starts its watch from)."""
        with self._lock:
            return self._rv

    # -- list / select ------------------------------------------------------

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        """List objects, optionally namespace-scoped and label-selected
        (selector semantics: all key=value pairs must match, ≙ labels.Set
        selectors used at mpi_job_controller.go:689-707)."""
        yield_point("store.list", kind)
        with self._lock:
            out = []
            for (k, ns, _), obj in self._objects.items():
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if selector:
                    lbls = _meta(obj).labels
                    if any(lbls.get(sk) != sv for sk, sv in selector.items()):
                        continue
                out.append(obj.deepcopy())
            out.sort(key=lambda o: (_meta(o).namespace, _meta(o).name))
            return out

    # -- watch --------------------------------------------------------------

    def watch(self, kind: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        """Returns a queue receiving WatchEvents for ``kind`` (None = all).
        The caller owns draining it; stop with stop_watch()."""
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        with self._lock:
            self._watchers.append((kind, q))
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            self._watchers = [(k, w) for (k, w) in self._watchers if w is not q]
