"""Thread-safe versioned object store with watches.

≙ the kube-apiserver + informer-cache layer the reference depends on. The
semantics preserved from the reference's usage:

- **resourceVersion optimistic concurrency**: updates with a stale
  resource_version raise Conflict (the reference relies on apiserver conflicts
  + requeue; our controller does the same).
- **Watches**: every create/update/delete fans out a WatchEvent to subscriber
  queues, which is what informers consume (≙ the event handlers registered in
  NewMPIJobController, v2/pkg/controller/mpi_job_controller.go:300-339).
- **Objects are deep-copied on the way in and out** so callers can never
  mutate the store's copy — the same rule as informer caches ("read-only +
  DeepCopy before mutation", SURVEY.md §5.2).
- **Label selection** for list operations (≙ the group/job-name selector the
  controller lists pods with, :689-707).
"""

from __future__ import annotations

import logging
import queue
import threading
import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class Conflict(RuntimeError):
    pass


class Unauthorized(PermissionError):
    """A store request was rejected for a missing/wrong bearer token
    (HTTP backend only; ≙ kube-apiserver authn rejecting a client,
    /root/reference/manifests/base/cluster-role.yaml being the authz side)."""


class Forbidden(PermissionError):
    """A store request authenticated with the READ-ONLY token tried to
    mutate (HTTP backend only; ≙ the aggregated view-vs-edit ClusterRole
    split of /root/reference/manifests/base/cluster-role.yaml:96-151 —
    a viewer physically cannot delete a job)."""


ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


def optimistic_update(store, kind, namespace, name, mutate, *,
                      attempts: int = 5, what: str = "update"):
    """get → ``mutate(copy)`` → non-force update, re-reading on Conflict.

    THE write pattern for fields shared between writers (eviction vs the
    reaper, cordon vs the heartbeat, unbind vs an executor launch): a forced
    write would clobber whichever concurrent transition lands first; this
    re-reads and re-checks instead. ``mutate(cur)`` edits the freshly-read
    object in place and returns True to proceed (False aborts — the
    precondition no longer holds on the current copy). Returns the committed
    object, or None when the object is missing, the precondition failed, or
    every attempt lost the race — exhaustion is logged, because callers are
    often one-shot (``ctl drain``, agent restart reconciliation) and would
    otherwise silently skip a live object."""
    for _ in range(attempts):
        try:
            cur = store.get(kind, namespace, name)
        except KeyError:  # NotFound subclasses KeyError
            return None
        if not mutate(cur):
            return None
        try:
            return store.update(cur)
        except KeyError:
            return None
        except Conflict:
            continue
    logging.getLogger("tpujob.machinery").warning(
        "%s: optimistic update of %s %s/%s lost the write race %dx; left as-is",
        what, kind, namespace, name, attempts,
    )
    return None


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    obj: Any


def _meta(obj: Any):
    return obj.metadata


class ObjectStore:
    """In-process apiserver equivalent. Keyed by (kind, namespace, name)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[Tuple[str, str, str], Any] = {}
        self._rv = 0
        self._watchers: List[Tuple[Optional[str], "queue.Queue[WatchEvent]"]] = []
        self._now = __import__("time").time

    # -- internal ----------------------------------------------------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _notify(self, etype: str, kind: str, obj: Any) -> None:
        for want_kind, q in list(self._watchers):
            if want_kind is None or want_kind == kind:
                q.put(WatchEvent(etype, kind, obj.deepcopy()))

    @staticmethod
    def _key(kind: str, namespace: str, name: str) -> Tuple[str, str, str]:
        return (kind, namespace, name)

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._lock:
            m = _meta(obj)
            k = self._key(obj.kind, m.namespace, m.name)
            if k in self._objects:
                raise AlreadyExists(f"{obj.kind} {m.namespace}/{m.name} already exists")
            obj = obj.deepcopy()
            m = _meta(obj)
            if not m.uid:
                m.uid = str(uuid.uuid4())
            m.resource_version = self._next_rv()
            if m.creation_timestamp is None:
                m.creation_timestamp = self._now()
            self._objects[k] = obj
            self._notify(ADDED, obj.kind, obj)
            return obj.deepcopy()

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            k = self._key(kind, namespace, name)
            if k not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return self._objects[k].deepcopy()

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def update(self, obj: Any, force: bool = False) -> Any:
        """Optimistic update; ``force=True`` skips the resource_version check
        (used by test fixtures playing kubelet, ≙ envtest's updatePodsToPhase,
        v2/test/integration/mpi_job_controller_test.go)."""
        with self._lock:
            m = _meta(obj)
            k = self._key(obj.kind, m.namespace, m.name)
            if k not in self._objects:
                raise NotFound(f"{obj.kind} {m.namespace}/{m.name} not found")
            current = self._objects[k]
            if not force and m.resource_version != _meta(current).resource_version:
                raise Conflict(
                    f"{obj.kind} {m.namespace}/{m.name}: resource_version "
                    f"{m.resource_version} != {_meta(current).resource_version}"
                )
            obj = obj.deepcopy()
            _meta(obj).resource_version = self._next_rv()
            self._objects[k] = obj
            self._notify(MODIFIED, obj.kind, obj)
            return obj.deepcopy()

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            k = self._key(kind, namespace, name)
            if k not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            obj = self._objects.pop(k)
            # deletion consumes a resource_version (kube does the same): every
            # watch event then carries a strictly increasing rv, which is what
            # the informer cache and the http watch ?resource_version= resume
            # anchor on — a DELETED event sharing the rv of the preceding
            # MODIFIED would be skippable on resume (a lost deletion)
            _meta(obj).resource_version = self._next_rv()
            self._notify(DELETED, kind, obj)
            return obj.deepcopy()

    def try_delete(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except NotFound:
            return None

    def current_rv(self) -> int:
        """The store's resource_version high-water mark. Watch-resume anchor:
        a consumer that has observed every event up to ``current_rv()`` holds
        a complete picture (≙ the list resourceVersion a kube Reflector
        starts its watch from)."""
        with self._lock:
            return self._rv

    # -- list / select ------------------------------------------------------

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        """List objects, optionally namespace-scoped and label-selected
        (selector semantics: all key=value pairs must match, ≙ labels.Set
        selectors used at mpi_job_controller.go:689-707)."""
        with self._lock:
            out = []
            for (k, ns, _), obj in self._objects.items():
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if selector:
                    lbls = _meta(obj).labels
                    if any(lbls.get(sk) != sv for sk, sv in selector.items()):
                        continue
                out.append(obj.deepcopy())
            out.sort(key=lambda o: (_meta(o).namespace, _meta(o).name))
            return out

    # -- watch --------------------------------------------------------------

    def watch(self, kind: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        """Returns a queue receiving WatchEvents for ``kind`` (None = all).
        The caller owns draining it; stop with stop_watch()."""
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        with self._lock:
            self._watchers.append((kind, q))
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            self._watchers = [(k, w) for (k, w) in self._watchers if w is not q]
