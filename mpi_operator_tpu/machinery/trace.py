"""Distributed tracing for the control plane — the causal observability seam.

The reference operator's observability stops at four promauto counters and
an event recorder; its roadmap punts tracing to "Horovod Timeline someday"
(PAPER.md §1). This module is the missing piece for THIS control plane,
whose interesting behavior is causal and cross-process: a `ctl create`
lands a store write, the watch carries it to the controller's informer,
the reconcile creates pods, the scheduler binds them, a node agent's
executor launches processes, failures ripple back as evictions and gang
restarts. Answering "why did job X restart, and where did the time go?"
requires stitching those hops together — which is exactly what spans with
parent links do.

Design (deliberately dependency-free — stdlib only, like everything else
in machinery/):

- **Trace = a job's lifetime.** Every TPUJob is stamped with a
  ``tpujob.dev/trace-id`` annotation at admission (api/client.py; the
  controller backstops direct store creates). The controller propagates
  the annotation onto the worker pods it creates, so ANY component holding
  a job-scoped object can open spans in the job's trace without a live
  header chain — robust across process crashes, which is the point.
- **Spans** are context managers (``with start_span(...)``): open →
  children parent to it via a thread-local stack → close → export. A bare
  ``start_span()`` call leaks an open span on the exception path, so the
  with-form is enforced by oplint rule OBS001.
- **Cross-process propagation** rides the store seam: HttpStoreClient
  injects a W3C-style ``traceparent`` header, StoreServer extracts it and
  opens a server-side span for the request, and every committed write's
  span context is remembered by resource_version so the watch event it
  produced carries ``(trace_id, span_id)`` to consumers. A reconcile
  triggered by a watch event therefore links back to the write that
  caused it (see ``set_delivery``/``get_delivery``).
- **Export** is a bounded in-process ring plus per-component JSONL files
  (``TPUJOB_TRACE_DIR``): each process appends finished spans to
  ``<component>-<pid>.jsonl``, flushed per line so a SIGKILLed process
  (the chaos suite's favorite) loses at most its open spans. The
  collector (``load_spans`` + ``render_timeline``) merges the files and
  renders the causal timeline ``ctl trace <job>`` prints.
- **Off by default, ~zero cost when off**: ``start_span`` returns a
  shared no-op span after one flag check. The ≤5% reconcile-overhead
  budget (PERF round 9) is measured with it ON.

Span-close sites double as the histogram instrumentation points
(opshell/metrics.py): reconcile latency, store request latency by
verb×backend, watch delivery lag, scheduler bind latency, replication
ship latency, failover duration — so the numbers PERF.md claims are the
numbers ``/metrics`` exports.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

log = logging.getLogger("tpujob.trace")

# the job annotation that names the trace (stamped at admission, propagated
# onto worker pods by the controller so every job-scoped component can join)
ANNOTATION_TRACE_ID = "tpujob.dev/trace-id"

# W3C trace-context header carried on the HTTP store seam
TRACEPARENT_HEADER = "traceparent"
ENV_TRACE_DIR = "TPUJOB_TRACE_DIR"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


# Ids are minted per span on the reconcile hot path: uuid.uuid4 costs
# ~8 µs and even os.urandom is a ~7 µs syscall per call — a per-thread
# PRNG seeded once from urandom gets the same collision odds for ~0.5 µs.
# The pid check re-seeds after a fork so two processes can never share a
# generator state (span ids are identifiers, not secrets).

_ids = threading.local()

# os.getpid() is a syscall (microseconds on sandboxed kernels) and the
# span path needs the pid three times per span — cache it, refreshed via
# the at-fork hook so a forked child can never reuse the parent's id
# generator state or stamp the parent's pid on its spans
_PID = os.getpid()


def _after_fork() -> None:
    global _PID
    _PID = os.getpid()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_after_fork)


def _id_rng():
    rng = getattr(_ids, "rng", None)
    if rng is None or getattr(_ids, "pid", None) != _PID:
        import random

        _ids.rng = rng = random.Random(os.urandom(16))
        _ids.pid = _PID
    return rng


def new_trace_id() -> str:
    return f"{_id_rng().getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_id_rng().getrandbits(64):016x}"


class SpanContext(tuple):
    """(trace_id, span_id) — the propagatable identity of a span. A plain
    tuple subclass so watch events can carry it (or a bare 2-tuple) over
    process boundaries without this module on the wire."""

    __slots__ = ()

    def __new__(cls, trace_id: str, span_id: str):
        return super().__new__(cls, (trace_id, span_id))

    @property
    def trace_id(self) -> str:
        return self[0]

    @property
    def span_id(self) -> str:
        return self[1]


def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx[0]}-{ctx[1]}-01"


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Strict parse (None for anything malformed — a bad header from a
    skewed client must degrade to 'no trace', never to a 500)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if not m:
        return None
    return SpanContext(m.group(1), m.group(2))


# sentinel for start_span(parent=ROOT): force a root span even when the
# calling thread has a span open — plain parent=None means "inherit the
# implicit stack parent", so rootness was otherwise inexpressible (a
# leaked-open span would silently adopt every later "root")
ROOT = object()


def _as_ctx(parent: Any) -> Optional[SpanContext]:
    """Normalize a parent argument: Span, SpanContext, (tid, sid) tuple,
    or None. Anything else (a corrupt wire value) degrades to None."""
    if parent is None:
        return None
    if isinstance(parent, Span):
        return parent.context()
    if isinstance(parent, SpanContext):
        return parent
    if (
        isinstance(parent, (tuple, list))
        and len(parent) == 2
        and all(isinstance(p, str) for p in parent)
    ):
        return SpanContext(parent[0], parent[1])
    return None


class Span:
    """One timed, attributed unit of work. Context-manager protocol:
    ``with start_span(...) as sp:`` — entry is a no-op (the span is already
    open and current), exit closes and exports it. oplint OBS001 enforces
    the with-form, because a span left open on an exception path stays on
    the thread's stack and silently re-parents everything after it."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "component",
        "start", "end", "attrs", "error", "_tracer", "_ended",
    )

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str], attrs: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.component = tracer.component
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.start = time.time()
        self.end: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.error: Optional[str] = None
        self._ended = False

    # -- identity ------------------------------------------------------------

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def adopt_trace(self, trace_id: Optional[str]) -> "Span":
        """Re-home this span (and the children opened after this call) into
        ``trace_id`` — the job-annotation anchor. Used by components whose
        span opens before the job-scoped object is read (the controller's
        reconcile): the causal parent edge (possibly into another trace)
        is kept, only the trace grouping moves."""
        if trace_id and trace_id != self.trace_id:
            self.trace_id = trace_id
        return self

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and self.error is None:
            self.error = f"{type(exc).__name__}: {exc}"
        self.finish()

    def finish(self) -> None:
        """Close and export. Idempotent; also defensively pops any child
        spans a non-with caller left open above us on the thread stack."""
        if self._ended:
            return
        self._ended = True
        self.end = time.time()
        self._tracer._pop(self)
        self._tracer._export(self.to_dict())

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "component": self.component,
            "pid": _PID,
            "start": self.start,
            "end": self.end,
            "attrs": self.attrs,
        }
        if self.error:
            d["error"] = self.error
        return d


class _NoopSpan:
    """The disabled-tracing span: every operation is a cheap no-op, one
    shared instance. Keeps call sites branch-free."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    attrs: Dict[str, Any] = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        return None

    def set_attr(self, key, value):
        return self

    def adopt_trace(self, trace_id):
        return self

    def context(self):
        return None

    def finish(self) -> None:
        return None


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Per-process span factory + exporter (module singleton ``TRACER``).

    The JSONL export is BUFFERED off the hot path: span close appends an
    encoded line to an in-memory list and a background flusher writes +
    flushes every ``FLUSH_INTERVAL`` (0.2 s) — per-span file I/O was the
    dominant tracing tax in the reconcile storm (the ≤5% overhead budget,
    PERF round 9). A SIGKILLed process therefore loses at most the last
    interval's spans plus its open ones; the chaos continuity test's
    anchor spans are all older than that by construction."""

    FLUSH_INTERVAL = 0.2
    # memory bound: past this many buffered spans the exporting thread
    # flushes inline rather than letting a stalled flusher grow the
    # buffer without limit (~4k spans ≈ 1-2 MB encoded)
    FLUSH_SPANS = 4096

    def __init__(self):
        self.enabled = False
        self.component = "unknown"
        self.ring_capacity = 2048
        self._ring: "collections.deque" = collections.deque(maxlen=2048)
        self._ring_lock = threading.Lock()
        self._dir: Optional[str] = None
        self._file = None
        self._file_lock = threading.Lock()
        self._buf: List[Dict[str, Any]] = []
        self._flusher: Optional[threading.Thread] = None
        self._flush_stop = threading.Event()
        self._local = threading.local()

    # -- configuration -------------------------------------------------------

    def configure(self, component: str, *, dir: Optional[str] = None,
                  ring_capacity: int = 2048, enabled: bool = True) -> "Tracer":
        """Turn tracing on for this process. ``dir`` adds the durable JSONL
        export (one ``<component>-<pid>.jsonl`` per process) the collector
        merges; without it spans live only in the in-process ring."""
        self.flush()  # spans buffered for the OLD dir must not vanish
        self.component = component
        self.ring_capacity = ring_capacity
        with self._ring_lock:
            self._ring = collections.deque(self._ring, maxlen=ring_capacity)
        with self._file_lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    log.debug("closing old trace export failed", exc_info=True)
                self._file = None
            self._buf = []
            self._dir = dir
        self.enabled = enabled
        if dir:
            # always a FRESH flusher generation with its own stop event:
            # re-checking the old thread's liveness would race its exit
            # (disable() just signalled it) and could leave tracing
            # re-enabled with NO cadence flusher — spans would reach disk
            # only at the inline threshold or atexit, i.e. a SIGKILL
            # loses everything since the reconfigure
            self._flush_stop.set()
            self._flush_stop = stop = threading.Event()
            self._flusher = threading.Thread(
                target=self._flush_loop, args=(stop,),
                name="trace-flush", daemon=True,
            )
            self._flusher.start()
            # clean exits (one-shot CLIs like `ctl create`) must not lose
            # the tail the interval-flusher hasn't reached yet
            import atexit

            atexit.register(self.flush)
        return self

    def configure_from_env(self, component: str) -> "Tracer":
        """The entry-point hook every process calls once: tracing turns on
        iff ``TPUJOB_TRACE_DIR`` is set (the chaos/e2e harnesses and real
        deployments both use it), exporting there."""
        d = os.environ.get(ENV_TRACE_DIR)
        if d:
            self.configure(component, dir=d)
        else:
            self.component = component
        return self

    def disable(self) -> None:
        self.enabled = False
        self.flush()
        self._flush_stop.set()
        with self._file_lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    log.debug("closing trace export failed", exc_info=True)
            self._file = None
            self._dir = None

    # -- thread-local span stack --------------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_span(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def current(self) -> Optional[SpanContext]:
        sp = self.current_span()
        return sp.context() if sp is not None else None

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if span in st:
            # pop through any children a non-with caller left open: the
            # stack must never keep a closed span as somebody's parent
            while st and st[-1] is not span:
                st.pop()
            if st:
                st.pop()

    # -- span creation -------------------------------------------------------

    def start_span(self, name: str, *, parent: Any = None,
                   trace_id: Optional[str] = None,
                   attrs: Optional[Dict[str, Any]] = None):
        """Open a span and make it current for this thread. ``parent``
        (Span / SpanContext / (tid, sid) tuple) overrides the implicit
        thread-stack parent — that's how cross-process causality (a watch
        delivery's origin, an extracted traceparent) is stitched in.
        ``trace_id`` pins the trace (the job-annotation anchor) regardless
        of where the parent edge points. ALWAYS use the with-form
        (oplint OBS001): a bare call leaks the span on exception paths."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is ROOT:
            pctx = None
        else:
            pctx = _as_ctx(parent)
            if pctx is None and parent is None:
                cur = self.current_span()
                if cur is not None:
                    pctx = cur.context()
        tid = trace_id or (pctx.trace_id if pctx else None) or new_trace_id()
        span = Span(self, name, tid, pctx.span_id if pctx else None, attrs)
        self._stack().append(span)
        return span

    # -- propagation helpers -------------------------------------------------

    def inject(self) -> Optional[str]:
        """traceparent header value for the current span (None = nothing
        to propagate; callers skip the header)."""
        ctx = self.current()
        return format_traceparent(ctx) if ctx is not None else None

    def current_ids(self) -> Optional[Tuple[str, str]]:
        """The current span context as a plain (trace_id, span_id) tuple —
        what store backends stamp onto the watch events a write produces."""
        ctx = self.current()
        return (ctx.trace_id, ctx.span_id) if ctx is not None else None

    # -- watch-delivery context ---------------------------------------------
    #
    # A watch consumer (informer drain, executor loop) sets the delivering
    # event's origin context here for the duration of its handlers; the
    # handler side (controller enqueue, scheduler wake, executor launch)
    # reads it to parent the work the event caused. Thread-local, so one
    # noisy stream never cross-contaminates another.

    def set_delivery(self, ctx: Any) -> None:
        self._local.delivery = _as_ctx(ctx)

    def get_delivery(self) -> Optional[SpanContext]:
        return getattr(self._local, "delivery", None)

    def clear_delivery(self) -> None:
        self._local.delivery = None

    # -- export --------------------------------------------------------------

    def _export(self, d: Dict[str, Any]) -> None:
        with self._ring_lock:
            self._ring.append(d)
        if self._dir is None:
            return
        # the hot path only appends the dict; the flusher thread does the
        # JSON encoding AND the file I/O — spans close in O(append)
        with self._file_lock:
            self._buf.append(d)
            inline_flush = len(self._buf) >= self.FLUSH_SPANS
        if inline_flush:
            self.flush()

    def _flush_loop(self, stop: threading.Event) -> None:
        # `stop` is THIS generation's event (passed in, never re-read from
        # self): a reconfigure signals exactly its own flusher
        while not stop.wait(self.FLUSH_INTERVAL):
            self.flush()

    def flush(self) -> None:
        """Encode + write + flush the buffered spans (flusher thread
        cadence, atexit, disable(), and the over-budget inline path)."""
        with self._file_lock:
            batch, self._buf = self._buf, []
            if not batch or self._dir is None:
                return
        lines = "\n".join(
            json.dumps(d, separators=(",", ":")) for d in batch
        )
        try:
            with self._file_lock:
                if self._dir is None:
                    return
                if self._file is None:
                    os.makedirs(self._dir, exist_ok=True)
                    path = os.path.join(
                        self._dir, f"{self.component}-{os.getpid()}.jsonl"
                    )
                    self._file = open(path, "a", encoding="utf-8")
                self._file.write(lines + "\n")
                self._file.flush()
        except OSError:
            # a full/readonly disk must never take the control plane down
            # with it — drop the durable export, keep the ring
            log.warning("trace export failed; disabling file export",
                        exc_info=True)
            with self._file_lock:
                self._dir = None
                self._file = None
                self._buf = []

    def ring(self) -> List[Dict[str, Any]]:
        with self._ring_lock:
            return list(self._ring)


TRACER = Tracer()

# module-level conveniences (the call-site API)
configure = TRACER.configure
configure_from_env = TRACER.configure_from_env
start_span = TRACER.start_span
current = TRACER.current
current_ids = TRACER.current_ids
inject = TRACER.inject
set_delivery = TRACER.set_delivery
get_delivery = TRACER.get_delivery
clear_delivery = TRACER.clear_delivery


# ---------------------------------------------------------------------------
# collector: merge per-process JSONL exports, render causal timelines
# ---------------------------------------------------------------------------


def load_spans(trace_dir: str) -> List[Dict[str, Any]]:
    """Every finished span exported under ``trace_dir``, merged across all
    per-process files, start-ordered. Torn tail lines (a process SIGKILLed
    mid-write) are skipped, not fatal. When THIS process exports to the
    same dir, its buffer is flushed first so a reader never races the
    0.2 s flush cadence; other processes' flushers run on their own."""
    if TRACER._dir:
        try:
            same = os.path.abspath(TRACER._dir) == os.path.abspath(trace_dir)
        except OSError:
            same = False
        if same:
            TRACER.flush()
    spans: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        path = os.path.join(trace_dir, name)
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        d = json.loads(line)
                    except ValueError:
                        continue  # torn tail from a killed process
                    if isinstance(d, dict) and d.get("span_id"):
                        spans.append(d)
        except OSError:
            log.debug("unreadable trace file %s", path, exc_info=True)
    spans.sort(key=lambda d: (d.get("start") or 0.0, d.get("span_id", "")))
    return spans


def spans_for_trace(spans: Iterable[Dict[str, Any]],
                    trace_id: str) -> List[Dict[str, Any]]:
    return [s for s in spans if s.get("trace_id") == trace_id]


def connected_components(spans: List[Dict[str, Any]],
                         link_traces: bool = False) -> List[set]:
    """Span-id sets connected by parent edges (cross-trace edges count —
    a NodeLost span caused evictions in several jobs' traces). With
    ``link_traces``, spans sharing a trace id are also connected: a trace
    IS one causal group by construction (the job annotation), so the
    chaos continuity test can assert the whole incident — job trace plus
    the cross-trace causes feeding it — is ONE component."""
    ids = {s["span_id"] for s in spans}
    parent = {s["span_id"]: s.get("parent_id") for s in spans}
    # union-find over the edge list
    root: Dict[str, str] = {i: i for i in ids}

    def find(x: str) -> str:
        while root[x] != x:
            root[x] = root[root[x]]
            x = root[x]
        return x

    for sid, pid in parent.items():
        if pid in ids:
            root[find(sid)] = find(pid)
    if link_traces:
        first_of_trace: Dict[str, str] = {}
        for s in spans:
            tid = s.get("trace_id") or ""
            if tid in first_of_trace:
                root[find(s["span_id"])] = find(first_of_trace[tid])
            else:
                first_of_trace[tid] = s["span_id"]
    comps: Dict[str, set] = {}
    for i in ids:
        comps.setdefault(find(i), set()).add(i)
    return sorted(comps.values(), key=len, reverse=True)


def render_timeline(all_spans: List[Dict[str, Any]], trace_id: str,
                    *, title: str = "") -> str:
    """The causal timeline `ctl trace` prints: the trace's spans as a
    parent-indented tree in start order, each with its offset from trace
    start, duration, component, and key attributes. A span whose parent
    lives in ANOTHER trace (the cross-trace causal edge — e.g. a gang
    restart caused by a NodeLost detection) is annotated with the causing
    span, which is how "why did this happen" reads straight off the
    output."""
    trace = spans_for_trace(all_spans, trace_id)
    if not trace:
        return f"no spans recorded for trace {trace_id}"
    by_id = {s["span_id"]: s for s in all_spans}
    in_trace = {s["span_id"] for s in trace}
    children: Dict[Optional[str], List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for s in trace:
        pid = s.get("parent_id")
        if pid in in_trace:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)
    t0 = min(s.get("start") or 0.0 for s in trace)
    lines = [title or f"trace {trace_id}"]

    def _dur(s: Dict[str, Any]) -> str:
        if s.get("end") is None:
            return "open"
        return f"{(s['end'] - s['start']) * 1e3:.1f}ms"

    def _attrs(s: Dict[str, Any]) -> str:
        attrs = s.get("attrs") or {}
        if not attrs:
            return ""
        body = " ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
        return f" [{body}]"

    def emit(s: Dict[str, Any], depth: int) -> None:
        off = (s.get("start", t0) - t0) * 1e3
        err = f" ERROR({s['error']})" if s.get("error") else ""
        lines.append(
            f"  {off:>9.1f}ms {'  ' * depth}{s.get('component', '?')}/"
            f"{s.get('name', '?')} {_dur(s)}{_attrs(s)}{err}"
        )
        pid = s.get("parent_id")
        if pid and pid not in in_trace and pid in by_id:
            cause = by_id[pid]
            lines.append(
                f"  {'':>11} {'  ' * depth}  ⇐ caused by "
                f"{cause.get('component', '?')}/{cause.get('name', '?')}"
                f"{_attrs(cause)}"
            )
        for child in sorted(
            children.get(s["span_id"], ()),
            key=lambda c: (c.get("start") or 0.0, c.get("span_id", "")),
        ):
            emit(child, depth + 1)

    for r in sorted(roots, key=lambda s: (s.get("start") or 0.0,
                                          s.get("span_id", ""))):
        emit(r, 0)
    return "\n".join(lines)


# incident span names `ctl trace --last-incident` anchors on. slo.alert
# is the SLO monitor's firing span (ISSUE 13): an alert IS an incident,
# and its span carries the flight-recorder bundle path as an attribute
_INCIDENT_NAMES = ("controller.gang_restart", "replica.election",
                   "monitor.node_lost", "slo.alert")


def last_incident(spans: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The most recent gang restart / failover / node loss span — the
    anchor `ctl trace --last-incident` reconstructs from."""
    incidents = [s for s in spans if s.get("name") in _INCIDENT_NAMES]
    if not incidents:
        return None
    return max(incidents, key=lambda s: s.get("start") or 0.0)


def render_incident(all_spans: List[Dict[str, Any]],
                    incident: Dict[str, Any]) -> str:
    """The incident's causal neighborhood: its ancestry chain (walking
    parent edges across traces — the NodeLost behind the eviction behind
    the restart), then the full trace it belongs to."""
    by_id = {s["span_id"]: s for s in all_spans}
    chain: List[Dict[str, Any]] = []
    seen = set()
    cur: Optional[Dict[str, Any]] = incident
    while cur is not None and cur["span_id"] not in seen:
        seen.add(cur["span_id"])
        chain.append(cur)
        cur = by_id.get(cur.get("parent_id") or "")
    lines = [
        f"last incident: {incident.get('component', '?')}/"
        f"{incident.get('name', '?')} at {incident.get('start', 0):.3f}",
        "causal chain (effect ← cause):",
    ]
    for s in chain:
        attrs = " ".join(
            f"{k}={v}" for k, v in sorted((s.get("attrs") or {}).items())
        )
        lines.append(
            f"  {s.get('component', '?')}/{s.get('name', '?')}"
            + (f" [{attrs}]" if attrs else "")
        )
    lines.append("")
    lines.append(render_timeline(all_spans, incident["trace_id"]))
    return "\n".join(lines)
