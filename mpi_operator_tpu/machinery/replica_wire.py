"""Wire replication fabric: the deployed twin of the in-process PeerHub.

PR 8 proved the replica set as a *store* — but its replication RPCs rode
:class:`~mpi_operator_tpu.machinery.replicated_store.PeerHub`, synchronous
method dispatch inside one process. This module closes the in-process/
deployed gap (ROADMAP item 3): :class:`HttpPeerFabric` duck-types the hub's
``call(src, dst, method, *args)`` surface over real sockets, so
``ReplicaNode`` runs UNCHANGED — the same election, lease, ship, and
snapshot-resync code paths the analysis gates (storecheck / linearize /
``crash --replica``) exercise in-process are the ones three ``tpu-store``
processes run in production.

Deployment shape (one process per replica; see README "Replicated store")::

    tpu-store --store sqlite:/var/lib/tpujob/n0.db --listen 0.0.0.0:8475 \\
        --replica-id n0 \\
        --peers n0=http://a:8475,n1=http://b:8475,n2=http://c:8475 \\
        --peer-token-file /etc/tpujob/peer.token

Protocol notes:

- Peer RPCs are POSTs to ``/v1/replica/{request-vote,append-entries,
  fetch-entries,install-snapshot,snapshot-chunk,snapshot-done}`` carrying
  ``{"src": <node>, "args": [...]}``; the server dispatches into its local
  node's handler (epoch fencing therefore runs SERVER-SIDE, in the
  handler, exactly as in-process) and answers ``{"result": ...}``.
- Auth is a dedicated PEER token tier: every peer route fails closed with
  a typed 403 for a missing/wrong token, and the admin/read/node tiers
  are explicitly NOT replication identities (StoreServer._peer_denied).
  The token rides the Authorization header only — never URLs or logs.
- Every RPC has a bounded per-peer timeout plus a small jittered retry:
  a hung peer costs a bounded slice of one ship and degrades the write
  to majority-only instead of wedging it (the PeerUnreachable contract).
- ``StaleEpoch`` crosses the wire as a typed 409 and is re-raised, so
  fencing works identically over sockets.
- Snapshots move as size-bounded chunks (replicated_store.snapshot_offer/
  snapshot_chunk): the receiving node PULLS them back through this same
  fabric, hash-verifies the assembled payload, and applies atomically —
  resumable at chunk granularity after a dropped connection.
"""

from __future__ import annotations

import argparse
import http.client
import json
import logging
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Iterable, List, Optional, Tuple

from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.replicated_store import (
    PeerUnreachable,
    ReplicaNode,
    StaleEpoch,
    UnknownTransfer,
    tick_node,
)

log = logging.getLogger("tpujob.replica.wire")

# RPC method → wire route. replica_status is deliberately absent: it is
# served by the public GET /v1/replica/status probe, not the peer tier.
PEER_ROUTES = {
    "request_vote": "request-vote",
    "append_entries": "append-entries",
    "fetch_entries": "fetch-entries",
    "install_snapshot": "install-snapshot",
    "snapshot_chunk": "snapshot-chunk",
    "snapshot_done": "snapshot-done",
}


def peer_wire_routes() -> List[str]:
    """The wire paths this fabric dials, sorted — the client-side mirror of
    StoreServer._PEER_ROUTE_METHODS. analysis/authzcheck.py diffs the two
    on every probe so a route added to one table but not the other is a
    finding before it is a 404 storm in a real failover."""
    return sorted("/v1/replica/" + wire for wire in PEER_ROUTES.values())


def parse_peer_map(spec: str, flag: str = "--peers") -> Dict[str, str]:
    """``'n0=http://a:8475,n1=http://b:8475'`` → {id: url}. Fails fast on
    malformed entries — a typo'd peer URL silently dropped would shrink
    the set's majority without anyone noticing."""
    out: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        nid, sep, url = part.partition("=")
        nid, url = nid.strip(), url.strip().rstrip("/")
        if not sep or not nid or not url.startswith(("http://", "https://")):
            raise ValueError(
                f"{flag} entries are 'id=http://host:port', got {part!r}"
            )
        if nid in out:
            raise ValueError(f"{flag}: duplicate replica id {nid!r}")
        out[nid] = url
    if len(out) < 2:
        raise ValueError(
            f"{flag} needs at least two entries (a replica set of one is "
            f"a standalone store — drop --replica-id instead)"
        )
    return out


class WireMembership:
    """The static-membership 'set view' a standalone wire replica needs:
    :class:`ReplicaNode` reads ``node_ids`` (peers + majority),
    ``advertise`` (dialable NotLeader hints + the `ctl store status`
    membership discovery), and records won elections. The deployed twin
    of :class:`ReplicaSet` minus the in-process node registry."""

    def __init__(self, node_ids: Iterable[str],
                 advertise: Dict[str, str]):
        self.node_ids = sorted(node_ids)
        self.advertise = dict(advertise)
        self.leadership_log: List[Tuple[int, str]] = []
        self._log_lock = threading.Lock()

    def _record_leader(self, epoch: int, node_id: str) -> None:
        with self._log_lock:
            self.leadership_log.append((epoch, node_id))


class HttpPeerFabric:
    """PeerHub's ``call`` surface over HTTP. One instance per process,
    owning the local node and the dial map to every peer."""

    def __init__(self, node_id: str, peer_urls: Dict[str, str],
                 peer_token: str, *, rpc_timeout: float = 3.0,
                 install_timeout: float = 120.0, retries: int = 1,
                 retry_base: float = 0.05, seed: int = 0):
        if not peer_token:
            # fail closed: an unauthenticated peer fabric would let anyone
            # who can dial the port rewrite the replicated history
            raise ValueError("HttpPeerFabric requires a peer token")
        self.node_id = node_id
        self.peer_urls = {
            nid: url.rstrip("/") for nid, url in peer_urls.items()
            if nid != node_id
        }
        self._token = peer_token
        self.rpc_timeout = rpc_timeout
        # install_snapshot blocks while the RECEIVER pulls the chunked
        # payload back through its own fabric — budget for the transfer,
        # not one round-trip (the caller runs it OFF the ship gate, so a
        # long transfer blocks only the resync pass, never writes)
        self.install_timeout = install_timeout
        self.retries = retries
        self.retry_base = retry_base
        self._rng = random.Random(f"fabric:{seed}:{node_id}")
        self._down = False
        self._local: Optional[ReplicaNode] = None
        self._stop = threading.Event()
        # peers whose auth rejection was already warned about: a token
        # misconfiguration must surface ONCE at WARNING per peer, not
        # drown as debug-level "unreachable" noise
        self._warned_auth: set = set()

    # -- hub surface ---------------------------------------------------------

    def register(self, node: ReplicaNode) -> None:
        self._local = node

    def set_down(self, node_id: str, down: bool) -> None:
        """Local crash semantics only (ReplicaNode.crash/reopen call this
        on themselves); a REMOTE peer's death is observed as connection
        refused, exactly like a real SIGKILL."""
        if node_id == self.node_id:
            self._down = down

    def call(self, src: str, dst: str, method: str, *args) -> Any:
        if self._down:
            raise PeerUnreachable(f"{self.node_id} is down")
        if dst == self.node_id:
            # a node pulling chunks may be handed its own id by a
            # confused config; dispatch locally rather than dialing self
            if self._local is None:
                raise PeerUnreachable(f"{dst} has no local node")
            return getattr(self._local, method)(*args)
        route = PEER_ROUTES.get(method)
        if route is None:
            raise ValueError(f"{method!r} is not a peer RPC")
        url = self.peer_urls.get(dst)
        if url is None:
            raise PeerUnreachable(f"unknown peer {dst!r}")
        body = json.dumps({"src": src, "args": list(args)}).encode()
        headers = {
            "Content-Type": "application/json",
            # the peer token rides ONLY this header — never a URL or a
            # log line (SEC001; pinned by the wire-capture test)
            "Authorization": "Bearer " + self._token,
        }
        traceparent = trace.inject()
        if traceparent:
            headers[trace.TRACEPARENT_HEADER] = traceparent
        timeout = (self.install_timeout if method == "install_snapshot"
                   else self.rpc_timeout)
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            req = urllib.request.Request(
                url + "/v1/replica/" + route, data=body, method="POST",
                headers=headers,
            )
            try:
                # the bounded timeout is the hung-peer fence: a stalled
                # socket costs at most (retries+1)×timeout per ship and
                # the write degrades to majority-only
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read())["result"]
            except urllib.error.HTTPError as e:
                payload: Dict[str, Any] = {}
                try:
                    payload = json.loads(e.read())
                except (ValueError, OSError):
                    pass  # non-JSON error body: generic unreachable below
                err = payload.get("error", "")
                if err == "StaleEpoch":
                    # the fence crosses the wire typed: the caller steps
                    # down exactly as it would in-process
                    raise StaleEpoch(int(payload.get("epoch", 0))) from None
                if err == "UnknownTransfer":
                    raise UnknownTransfer(
                        payload.get("message", "transfer gone")
                    ) from None
                if e.code in (401, 403) and dst not in self._warned_auth:
                    # an auth rejection is a CONFIGURATION fault, not a
                    # network one — without this line a mismatched
                    # --peer-token-file reads exactly like a dead fabric
                    # (no leader ever elected, nothing above debug level)
                    self._warned_auth.add(dst)
                    log.warning(
                        "peer %s rejected this node's peer token (%s %s):"
                        " check --peer-token-file on both ends",
                        dst, e.code, payload.get("error", ""),
                    )
                last = PeerUnreachable(
                    f"peer {dst} answered {e.code} "
                    f"{payload.get('error', '')}".strip()
                )
            except (urllib.error.URLError, OSError,
                    http.client.HTTPException) as e:
                # refused / reset / timed out: indistinguishable from a
                # dead or partitioned peer — PeerUnreachable, the same
                # signal PeerHub raises
                last = PeerUnreachable(f"peer {dst} unreachable: {e}")
            if attempt < self.retries:
                # small jittered retry: a transient reset heals without
                # failing the ship; the budget stays bounded
                if self._stop.wait(
                    self.retry_base * (1 + self._rng.random())
                ):
                    break
        raise last if last is not None else PeerUnreachable(
            f"peer {dst} unreachable"
        )

    def close(self) -> None:
        self._stop.set()


class ReplicaTicker:
    """Per-process auto mode: the same renew-or-campaign loop
    :class:`ReplicaSet` runs in-process, for the ONE local node."""

    def __init__(self, node: ReplicaNode, *, retry_period: float = 0.25,
                 seed: int = 0):
        self.node = node
        self.retry_period = retry_period
        self._index = node.rset.node_ids.index(node.node_id)
        self._rng = random.Random(f"{seed}:{node.node_id}")
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=f"replica-tick-{node.node_id}",
            daemon=True,
        )

    def start(self) -> "ReplicaTicker":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.retry_period):
            try:
                tick_node(self.node, self._rng, self._index,
                          self.retry_period, self._stop)
            except Exception:
                # a dead ticker would silently end failover; survive
                # transient RPC errors (a peer dying mid-call)
                log.debug("replica ticker error", exc_info=True)


def build_wire_replica(
    replica_id: str, db_path: str, peers: Dict[str, str],
    peer_token: str, *, advertise: Optional[Dict[str, str]] = None,
    lease_duration: float = 2.0, retry_period: float = 0.25,
    poll_interval: float = 0.05, seed: int = 0,
    rpc_timeout: float = 3.0,
) -> Tuple[ReplicaNode, ReplicaTicker]:
    """Assemble one wire replica: membership view + HTTP fabric + node +
    ticker. ``peers`` is the DIAL map (may route through chaos proxies);
    ``advertise`` is the PUBLIC map clients should be hinted at (defaults
    to ``peers``)."""
    if replica_id not in peers:
        raise ValueError(
            f"--replica-id {replica_id!r} is not in the --peers map "
            f"({sorted(peers)})"
        )
    membership = WireMembership(peers, dict(advertise or peers))
    fabric = HttpPeerFabric(
        replica_id, peers, peer_token, rpc_timeout=rpc_timeout, seed=seed,
    )
    node = ReplicaNode(
        replica_id, db_path, fabric, membership,
        lease_duration=lease_duration, poll_interval=poll_interval,
    )
    fabric.register(node)
    ticker = ReplicaTicker(node, retry_period=retry_period, seed=seed)
    return node, ticker


# ---------------------------------------------------------------------------
# smoke: 3 real processes, one failover, one cold join (<30 s)
# ---------------------------------------------------------------------------


def free_ports(n: int) -> List[int]:
    """Reserve ``n`` distinct loopback ports: every socket stays OPEN
    until all are bound (sequential bind-close pairs can be handed the
    same ephemeral port twice). Shared by the smoke and the torture
    bench."""
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def probe_replica_status(url: str, timeout: float = 2.0
                         ) -> Optional[Dict[str, Any]]:
    """Best-effort /v1/replica/status probe (None when unreachable) —
    shared by the smoke, the torture bench, and anything else that needs
    to find the leader among known endpoints without a full client."""
    try:
        with urllib.request.urlopen(
            url + "/v1/replica/status", timeout=timeout
        ) as r:
            return json.loads(r.read())
    except (urllib.error.URLError, OSError, ValueError):
        return None


def wait_for_wire_leader(urls: Dict[str, str], timeout: float = 20.0
                         ) -> Optional[str]:
    """Poll ``{node_id: url}`` until some node reports itself leader;
    returns its id (None on timeout)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for nid, url in urls.items():
            st = probe_replica_status(url)
            if st and st.get("role") == "leader":
                return nid
        time.sleep(0.05)
    return None


def smoke(keep_dir: Optional[str] = None) -> Dict[str, Any]:
    """The wire-replica smoke the verify gate runs: spawn three real
    ``tpu-store`` replica processes, write through the multi-endpoint
    client, SIGKILL the leader (every acked write must survive failover
    at its exact rv), then COLD-JOIN the killed node — db wiped — and
    wait for it to converge to the leader's exact rv (snapshot or tail
    catch-up over the wire). Prints nothing; returns the result dict."""
    from mpi_operator_tpu.machinery.http_store import HttpStoreClient
    from mpi_operator_tpu.machinery.objects import ConfigMap
    from mpi_operator_tpu.api.types import ObjectMeta

    tmp = keep_dir or tempfile.mkdtemp(prefix="replica-smoke-")
    tok_path = os.path.join(tmp, "peer.token")
    with open(tok_path, "w") as f:
        f.write("smoke-peer-secret\n")
    ports = free_ports(3)
    ids = [f"n{i}" for i in range(3)]
    urls = {nid: f"http://127.0.0.1:{p}" for nid, p in zip(ids, ports)}
    peers = ",".join(f"{nid}={urls[nid]}" for nid in ids)
    env = dict(os.environ)
    env.setdefault("PYTHONPATH",
                   os.path.dirname(os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__)))))
    procs: Dict[str, subprocess.Popen] = {}

    def spawn(nid: str) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "mpi_operator_tpu.machinery.http_store",
             "--store", f"sqlite:{os.path.join(tmp, nid + '.db')}",
             "--listen", f"127.0.0.1:{ports[ids.index(nid)]}",
             "--replica-id", nid, "--peers", peers,
             "--peer-token-file", tok_path,
             "--replica-lease-duration", "0.5",
             "--replica-retry-period", "0.05"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    out: Dict[str, Any] = {"metric": "replica_wire_smoke", "ok": False}
    client = None
    try:
        for nid in ids:
            procs[nid] = spawn(nid)
        lead = wait_for_wire_leader(urls, 15.0)
        if lead is None:
            out["error"] = "no initial leader"
            return out
        client = HttpStoreClient(
            list(urls.values()), timeout=5.0, conn_refused_retries=10,
        )
        acked: Dict[str, int] = {}
        for i in range(20):
            o = client.create(ConfigMap(metadata=ObjectMeta(
                name=f"smoke-{i:02d}", namespace="smoke")))
            acked[o.metadata.name] = o.metadata.resource_version
        # SIGKILL the leader mid-set; the survivors must elect and ack
        procs[lead].send_signal(signal.SIGKILL)
        procs[lead].wait()
        t0 = time.monotonic()
        post = 0
        deadline = time.monotonic() + 20.0
        while post < 5 and time.monotonic() < deadline:
            try:
                o = client.create(ConfigMap(metadata=ObjectMeta(
                    name=f"post-{post:02d}", namespace="smoke")))
                acked[o.metadata.name] = o.metadata.resource_version
                post += 1
            except Exception:
                # the leaderless window: refused/421/503 until a survivor
                # takes the lease — that wait IS what the smoke measures
                log.debug("post-failover write not yet acked",
                          exc_info=True)
                time.sleep(0.1)
        out["failover_ms"] = round((time.monotonic() - t0) * 1e3, 1)
        new_lead = wait_for_wire_leader(urls, 15.0)
        if new_lead is None or new_lead == lead or post < 5:
            out["error"] = f"failover failed (leader={new_lead}, post={post})"
            return out
        # every acked write present at its exact rv on the new leader
        for name, rv in acked.items():
            got = client.get("ConfigMap", "smoke", name)
            if got.metadata.resource_version != rv:
                out["error"] = (f"{name}: acked rv {rv}, "
                                f"got {got.metadata.resource_version}")
                return out
        # cold join: wipe the killed node's db and respawn — it must
        # converge to the leader's exact rv over the wire
        for suffix in ("", "-wal", "-shm"):
            p = os.path.join(tmp, lead + ".db" + suffix)
            if os.path.exists(p):
                os.unlink(p)
        t1 = time.monotonic()
        procs[lead] = spawn(lead)
        lead_rv = None
        joined = False
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            st_new = probe_replica_status(urls[new_lead])
            st_join = probe_replica_status(urls[lead])
            if st_new and st_join:
                lead_rv = st_new.get("applied_rv")
                if (st_join.get("role") == "follower"
                        and st_join.get("applied_rv") == lead_rv):
                    joined = True
                    break
            time.sleep(0.05)
        out["cold_join_ms"] = round((time.monotonic() - t1) * 1e3, 1)
        if not joined:
            out["error"] = "cold join never converged"
            return out
        out.update(ok=True, writes=len(acked), leader_killed=lead,
                   new_leader=new_lead, converged_rv=lead_rv)
        return out
    finally:
        if client is not None:
            client.close()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait()
        if keep_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="replica-wire",
        description="Wire-replica utilities (the deployed HA fabric).",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="spawn 3 real tpu-store replica processes, "
                         "SIGKILL the leader, cold-join it back with a "
                         "wiped db; exit 0 iff every acked write survived "
                         "at its exact rv and the joiner converged")
    args = ap.parse_args(argv)
    if not args.smoke:
        ap.print_help()
        return 2
    out = smoke()
    print(json.dumps(out), flush=True)
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
