"""Cluster-state machinery: the k8s-apimachinery-equivalent substrate.

The reference operator leans on kube-apiserver + client-go: typed objects with
ObjectMeta/ownerReferences, informer caches with event handlers, rate-limited
workqueues, and an event recorder (wired in NewMPIJobController,
/root/reference/v2/pkg/controller/mpi_job_controller.go:248-341). This package
provides the same substrate as an in-process, thread-safe object store so the
TPU controller can be developed and tested exactly like the reference's
envtest tier (SURVEY.md §4.2) without a cluster — and so a future remote
backend (real k8s, GKE TPU provisioner) can slot in behind the same interface.
"""

from mpi_operator_tpu.machinery.objects import (  # noqa: F401
    ConfigMap,
    Event,
    Pod,
    PodGroup,
    PodPhase,
    PodSpec,
    PodStatus,
    Service,
    ServiceSpec,
)
from mpi_operator_tpu.machinery.store import (  # noqa: F401
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
    WatchEvent,
)
from mpi_operator_tpu.machinery.workqueue import RateLimitingQueue  # noqa: F401
from mpi_operator_tpu.machinery.events import EventRecorder  # noqa: F401
from mpi_operator_tpu.machinery.cache import InformerCache, Lister  # noqa: F401
