"""Dependent-object types the controller materializes.

≙ the corev1/volcano objects the reference reconciler creates for each MPIJob
(v2/pkg/controller/mpi_job_controller.go): worker/launcher Pods (:1246-1392),
headless Service (:1141-1171), ConfigMap (:1088-1138), PodGroup (:1215-1237),
and the Events recorded throughout. Secrets (SSH keys, :1175-1210) have no TPU
analogue — rendezvous replaces rank-spawn — so there is no Secret type.

Only the fields the framework actually schedules/observes are modeled; each
type reuses the api ObjectMeta so ownership/adoption logic is uniform.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from mpi_operator_tpu.api.types import Condition, Container, ObjectMeta, _Dictable
from mpi_operator_tpu.machinery.store import Conflict, NotFound


class PodPhase:
    """≙ corev1.PodPhase, the signal updateMPIJobStatus consumes
    (mpi_job_controller.go:921-996)."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"

    ALL_VALUES = (PENDING, RUNNING, SUCCEEDED, FAILED)


# eviction reason for planned maintenance moves (the disruption plane's
# checkpoint-then-migrate verb): retryable like "Evicted", free like
# "Preempted" — the move is the infrastructure's doing, so it advances
# restart_generation but never restart_count
REASON_MAINTENANCE = "Maintenance"


@dataclass
class PodSpec(_Dictable):
    container: Container = field(default_factory=Container)
    hostname: str = ""
    subdomain: str = ""
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    restart_policy: str = "Never"
    scheduler_name: str = ""
    priority_class: str = ""


@dataclass
class PodStatus(_Dictable):
    phase: str = PodPhase.PENDING
    ready: bool = False
    reason: str = ""
    message: str = ""
    exit_code: Optional[int] = None
    pod_ip: str = ""
    host_ip: str = ""
    start_time: Optional[float] = None
    # where the executor streams this pod's stdout (stderr sits next to it
    # with a .err suffix) — the kubelet-log-dir equivalent that `ctl logs`
    # reads; the path is local to the node named in spec.node_name
    log_path: str = ""
    # serving-pod telemetry the executor mirrors alongside the phase
    # (qps / queue_depth / p99_ms): the per-pod sample stream the serve
    # autoscaler aggregates — kubelet resource-metrics shaped, carried in
    # status so it rides the existing patch-batch machinery and watch
    # fan-out instead of needing a second metrics pipeline
    serve_stats: Optional[Dict[str, float]] = None
    # training-pod telemetry, the batch twin of serve_stats (the workload
    # telemetry plane, ISSUE 15): cumulative stall-attributed wall-second
    # buckets + step counters this incarnation, mirrored by the executor
    # from the worker's step-stats file (runtime/stepstats.py) or scripted
    # by a hollow timeline. ALWAYS built through bounded_train_stats —
    # an unbounded dict here would bloat every watch event carrying the
    # pod (oplint OBS004)
    train_stats: Optional[Dict[str, object]] = None


@dataclass
class Pod(_Dictable):
    kind: str = "Pod"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def is_finished(self) -> bool:
        return self.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)

    def is_evicted(self) -> bool:
        """≙ isEvicted check on launcher pods (status.go:99-106 + controller
        :935-950): Failed with an eviction-flavored reason. Covers
        infrastructure eviction (node loss, drain), priority preemption,
        and planned maintenance moves — all always-retryable."""
        return self.status.phase == PodPhase.FAILED and self.status.reason in (
            "Evicted", "Preempted", REASON_MAINTENANCE,
        )

    def is_preempted(self) -> bool:
        """Preemption specifically: retryable like any eviction, but it must
        NOT burn the job's backoffLimit — being preempted is the scheduler's
        doing, not the workload failing (kube preemption never counts
        against a Job's restart policy either)."""
        return (
            self.status.phase == PodPhase.FAILED
            and self.status.reason == "Preempted"
        )

    def is_planned_disruption(self) -> bool:
        """The free-restart class: preemption AND maintenance migration.
        Both are the control plane's doing — a job moved off a node with a
        maintenance window must not burn its backoffLimit budget any more
        than a preempted one (the DrainController's checkpoint-then-migrate
        contract: restart_generation advances, restart_count does not)."""
        return self.status.phase == PodPhase.FAILED and self.status.reason in (
            "Preempted", REASON_MAINTENANCE,
        )


@dataclass
class ServiceSpec(_Dictable):
    cluster_ip: str = "None"  # headless, ≙ newWorkersService :1141-1147
    selector: Dict[str, str] = field(default_factory=dict)


@dataclass
class Service(_Dictable):
    kind: str = "Service"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)


@dataclass
class ConfigMap(_Dictable):
    kind: str = "ConfigMap"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class PodGroupSpec(_Dictable):
    min_member: int = 0
    # priority class name or integer string; resolved by the scheduler
    # (scheduler/gang.py resolve_priority_class) to order pending gangs
    priority_class: str = ""


@dataclass
class PodGroup(_Dictable):
    """Gang-scheduling unit, ≙ volcano PodGroup (newPodGroup :1215-1237).
    On TPU this doubles as the slice-allocation request: min_member hosts that
    must be placed atomically on one slice."""

    kind: str = "PodGroup"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)


# Nodes are cluster-scoped in kubernetes; this store is namespaced, so they
# live under one well-known pseudo-namespace
NODE_NAMESPACE = "nodes"

# The planned-disruption notice contract (the disruption plane, ISSUE 14):
# a node carrying this annotation has a maintenance window — the value is
# the ABSOLUTE unix timestamp the hardware goes away. Stamped by
# `ctl drain <node> [--deadline S]` or a hollow fleet's seeded maintenance
# schedule; consumed by the DrainController (cordon → migrate → escalate
# at the deadline), the scheduler (imminent-maintenance placement penalty)
# and the node monitor (drain-owned nodes are not double-evicted).
# Cleared by `ctl uncordon` when the node returns from maintenance.
ANNOTATION_MAINTENANCE_AT = "tpujob.dev/maintenance-at"

# The sick-hardware flag (the rescheduler, ISSUE 18): stamped on a node
# when the goodput plane names one of its pods a straggler and the
# rescheduler moves the gang off it. Value is the unix timestamp of the
# flagging. The scheduler DEPRIORITIZES flagged nodes (middle placement
# tier: clean > straggler-flagged > maintenance-doomed) rather than
# excluding them — suspected-slow hardware still hosts when nothing
# else has room. Cleared by `ctl uncordon` once the host is vindicated
# or repaired (runbook row "rescheduler migrating too much").
ANNOTATION_STRAGGLER_NODE = "tpujob.dev/straggler-node"


class NodeConditionType:
    """Node conditions (operator-owned, like the cordon flag):

    Draining — an active maintenance drain is evacuating this node. Set by
    the DrainController when it adopts a maintenance notice; flipped
    inactive (reason=Drained) once no live pod remains bound.
    """

    DRAINING = "Draining"

    ALL_VALUES = (DRAINING,)


def maintenance_at(node: "Node"):
    """The node's maintenance deadline as a float, or None when absent or
    unparseable (a malformed stamp is surfaced by the DrainController as a
    warning Event, never silently treated as a real window)."""
    raw = node.metadata.annotations.get(ANNOTATION_MAINTENANCE_AT)
    if raw is None:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


def node_has_maintenance(node: "Node") -> bool:
    return ANNOTATION_MAINTENANCE_AT in node.metadata.annotations


def node_draining(node: "Node") -> bool:
    """True while the Draining condition is active (an in-flight drain)."""
    for c in node.status.conditions:
        if c.type == NodeConditionType.DRAINING:
            return bool(c.status)
    return False

# The single-process binding sentinel: the scheduler binds to it when no
# Node objects exist (dev/standalone shape), the LocalExecutor claims it,
# and agents must REJECT it as an identity. A cross-plane contract, so it
# lives here rather than inside the scheduler package.
LOCAL_NODE = "local"


@dataclass
class NodeStatus(_Dictable):
    # where this node can be reached (coordinator rendezvous resolution —
    # the headless-service-DNS role the reference gets from kube DNS,
    # ≙ newWorkersService :1141-1171 giving workers stable resolvable names)
    address: str = ""
    # base URL of the node agent's log endpoint; the agent stamps
    # f"{log_url}/<file>" into pod.status.log_path so `ctl logs` reads
    # cross-node (≙ `kubectl logs` riding the kubelet API)
    log_url: str = ""
    last_heartbeat: float = 0.0
    ready: bool = False
    # cordon flag (≙ kubectl cordon / node.spec.unschedulable): set by
    # `ctl cordon/drain`, PRESERVED across agent heartbeats, cleared by
    # `ctl uncordon`. A cordoned node keeps running its pods (drain evicts
    # them) but receives no new bindings.
    unschedulable: bool = False
    # chips this node can host (None = unbounded); the scalar-mode gang
    # scheduler admits against the sum over live nodes
    capacity_chips: Optional[int] = None
    # operator-owned conditions (the Draining state machine); like the
    # cordon flag, the NODE token tier may not touch these — agents
    # heartbeat via merge-patches that never mention the key
    conditions: List[Condition] = field(default_factory=list)


@dataclass
class Node(_Dictable):
    """A registered execution node (the kubelet's Node object). Node agents
    (executor/agent.py) self-register and heartbeat; the NodeMonitor marks
    stale nodes NotReady and evicts their pods (≙ the node controller's
    pod eviction that the reference leans on for worker-loss recovery)."""

    kind: str = "Node"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus = field(default_factory=NodeStatus)


@dataclass
class ObjectRef(_Dictable):
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class Event(_Dictable):
    """≙ corev1.Event as used by the reference's recorder (user-facing audit
    log, asserted by the integration eventChecker, v2/test/integration/
    main_test.go:116-178)."""

    kind: str = "Event"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved: ObjectRef = field(default_factory=ObjectRef)
    type: str = "Normal"  # Normal | Warning
    reason: str = ""
    message: str = ""
    timestamp: float = 0.0


def patch_pod_status(
    store,
    namespace: str,
    name: str,
    uid: str,
    changes: Dict,
    *,
    expected_rv=None,
    attempts: int = 5,
    what: str = "patch-pod-status",
):
    """THE pod status-mirror write (kubelet semantics over the PATCH verb),
    shared by the executor's phase mirror and evict_pod so the guards can
    never fork:

    - **incarnation guard**: ``uid`` must still match — a gang restart
      deleting and recreating the pod same-name must not inherit its
      predecessor's exit;
    - **write-once terminal**: a finished pod is never overwritten (an
      external eviction's retryable reason must survive the reaper of the
      process the eviction then killed).

    Fast path: when the caller holds a snapshot it already verified the
    guards against, ``expected_rv`` rides the patch as an rv precondition —
    a match PROVES the object is byte-identical to that snapshot, so the
    guards hold and the write is ONE request (no GET leg, the
    GET+PUT+409-retry loop collapsed). Only on Conflict does it fall back
    to read-and-re-check, which is exactly what the old loop did every
    time. Returns the committed pod, or None when the pod is gone, a new
    incarnation, or already terminal."""
    body = {"status": dict(changes)}
    if expected_rv:
        try:
            return store.patch(
                "Pod", namespace, name,
                {"metadata": {"resource_version": expected_rv}, **body},
                subresource="status",
            )
        except NotFound:
            return None
        except Conflict:
            pass  # snapshot went stale: re-read and re-check the guards
    for _ in range(attempts):
        try:
            cur = store.get("Pod", namespace, name)
        except NotFound:
            return None
        if uid and cur.metadata.uid != uid:
            return None
        if cur.is_finished():
            return None
        try:
            return store.patch(
                "Pod", namespace, name,
                {"metadata": {
                    "resource_version": cur.metadata.resource_version,
                 }, **body},
                subresource="status",
            )
        except NotFound:
            return None
        except Conflict:
            continue
    import logging

    logging.getLogger("tpujob.machinery").warning(
        "%s: status patch of Pod %s/%s lost the write race %dx; left as-is",
        what, namespace, name, attempts,
    )
    return None


def evict_pod(store, pod: "Pod", message: str, *,
              reason: str = "Evicted") -> bool:
    """Mark a pod Evicted — THE eviction primitive (reason=Evicted is what
    controller._pod_retryable treats as always-retryable, driving the
    gang-coherent restart). Shared by the node monitor (lost nodes),
    `ctl drain`, and the agent's restart reconciliation so the semantics
    can never fork. Returns False when the pod is already gone/finished.
    Callers own their own events/metrics.

    Rides patch_pod_status: the caller's snapshot anchors the rv fast
    path, so the common eviction is one status-subresource PATCH — which
    also means the NODE token tier can evict its own pods without
    full-object write rights."""
    if pod.is_finished():
        # the snapshot itself is terminal: the rv fast path would otherwise
        # trust it and overwrite the write-once terminal status
        return False
    return patch_pod_status(
        store, pod.metadata.namespace, pod.metadata.name, pod.metadata.uid,
        {
            "phase": PodPhase.FAILED,
            "ready": False,
            "reason": reason,  # "Evicted" | "Preempted" (is_evicted)
            "message": message,
        },
        expected_rv=pod.metadata.resource_version,
        what="evict_pod",
    ) is not None


# The on-demand profiling contract (the workload telemetry plane, ISSUE
# 15): `ctl profile <job> --steps N` stamps this TPUJob annotation with a
# JSON request ({"id", "steps", "at"}); the controller projects it into
# the job ConfigMap's "profile" key (the same membership channel the
# elastic protocol already polls), each worker captures a jax.profiler
# trace for N steps into the job's artifact dir and acks completion
# through its train_stats "profile" entry. `ctl profile --status/--fetch`
# read the acks back. Cleared by stamping a new request (one in-flight
# request per job; the id disambiguates).
ANNOTATION_PROFILE_REQUEST = "tpujob.dev/profile-request"


# ---------------------------------------------------------------------------
# bounded status-stats blobs (the workload telemetry plane, ISSUE 15)
# ---------------------------------------------------------------------------

# the stall-attribution bucket taxonomy — every wall-second of a training
# step classifies into exactly one of these (worker-side) or "restart"
# (controller-side downtime, charged from conditions by the goodput
# aggregator). Shared by the real step loop (runtime/stepstats.py), the
# hollow timelines, and the aggregator, so the attribution can never fork.
TRAIN_BUCKETS = ("compile", "input", "compute", "sync", "ckpt")
# the controller-side bucket: wall time a job spent torn down between
# generations (evict → relaunch), which no worker process can observe
BUCKET_RESTART = "restart"

_PROFILE_KEYS = ("id", "state", "dir")


def _r3(v) -> float:
    try:
        return round(float(v), 3)
    except (TypeError, ValueError):
        return 0.0


def _i(v) -> int:
    try:
        return int(v or 0)
    except (TypeError, ValueError):
        return 0


def bounded_serve_stats(qps=0.0, queue_depth=0.0, p99_ms=0.0,
                        **_ignored) -> Dict[str, float]:
    """THE constructor for a pod's ``status.serve_stats`` blob (oplint
    OBS004): exactly three rounded floats, whatever the caller passed.
    Status blobs ride EVERY watch event delivering the pod, so their size
    is a fan-out multiplier — bounding happens at construction, not by
    reviewer vigilance."""
    return {
        "qps": _r3(qps),
        "queue_depth": _r3(queue_depth),
        "p99_ms": _r3(p99_ms),
    }


def bounded_train_stats(step=0, steps=0, step_p50_ms=0.0, buckets=None,
                        profile=None, compile_cache=None,
                        **_ignored) -> Dict[str, object]:
    """THE constructor for a pod's ``status.train_stats`` blob (oplint
    OBS004). Fixed key set, rounded floats, bucket keys clamped to the
    :data:`TRAIN_BUCKETS` taxonomy, profile ack clamped to short strings
    — an unbounded dict here would bloat every watch event carrying the
    pod (the same reason serve_stats is three floats).

    ``step`` is the global step (survives restarts via checkpoint
    resume); ``steps`` counts steps run by THIS incarnation and
    ``buckets`` are THIS incarnation's cumulative attributed seconds —
    both reset on relaunch, which the aggregator's reset-aware deltas
    expect (like a Prometheus counter across a process restart)."""
    # the source may be a file written by an UNTRUSTED workload process
    # (the executor mirrors whatever the worker flushed): wrong-typed
    # fields degrade to zeros/absence, never an exception out of the
    # executor's poll loop
    if not isinstance(buckets, dict):
        buckets = {}
    out: Dict[str, object] = {
        "step": _i(step),
        "steps": _i(steps),
        "step_p50_ms": _r3(step_p50_ms),
        "buckets": {
            k: _r3(buckets.get(k, 0.0)) for k in TRAIN_BUCKETS
        },
    }
    if isinstance(profile, dict) and profile:
        out["profile"] = {
            k: str(profile.get(k, ""))[:256] for k in _PROFILE_KEYS
        }
    if isinstance(compile_cache, dict) and compile_cache:
        # persistent-compile-cache hit/miss counts (ISSUE 16): present
        # only when the worker configured the cache, so the `compile`
        # bucket can be read as warm (hits, near-zero seconds) vs cold
        # (misses, the full warmup). Two bounded ints, per incarnation.
        out["compile_cache"] = {
            "hits": _i(compile_cache.get("hits")),
            "misses": _i(compile_cache.get("misses")),
        }
    return out


KINDS = ("TPUJob", "TPUServe", "Alert", "Pod", "Service", "ConfigMap",
         "PodGroup", "Event", "Node")
