"""HTTP store: the multi-node store backend (the etcd/apiserver seam).

The deployment matrix (deploy/README.md) had one unfilled row: multi-node.
SqliteStore is honest about its scope — one node, writers serialized by the
file lock. The reference's multi-node story is the kube-apiserver + etcd
pair every component talks to over the network
(/root/reference/manifests/base/deployment.yaml; the clientsets of
v2/pkg/client/). This module is that pair for this framework:

- ``StoreServer`` wraps ANY backing store (ObjectStore for in-memory,
  SqliteStore for durability) and serves the duck-typed store surface over
  HTTP — the one process that owns the data, like etcd.
- ``HttpStoreClient`` implements the *same* create/get/update/delete/list/
  watch surface over the wire, so operator replicas, CLIs, and executors on
  **other nodes** plug in unchanged (`--store http://host:8475`). Components
  never see the backend — the same duck-typing contract as
  machinery/store.py and machinery/sqlite_store.py.

Watch semantics match the file-backed store: the server keeps a bounded
in-memory event log with contiguous sequence numbers; clients long-poll
``/v1/watch?after=N``. Every event also carries the object's (strictly
increasing) resource_version, and a client whose seq cursor is invalid —
server restarted, fell off the retention window — may present
``?resource_version=N`` to resume: the server replays the ring from the
first event with rv > N when it can prove completeness, and otherwise
falls back to a relist (every live object as MODIFIED) — the kube
"resourceVersion too old" (410 Gone) → relist contract, same recovery
path as SqliteStore._relist_to. The informer cache (machinery/cache.py)
rides this seam: lister reads come from the watch-fed cache, so the store
sees only writes and one long-poll, not a LIST per reconcile.

Run standalone (the etcd-equivalent process):

  python -m mpi_operator_tpu.machinery.http_store \\
      --store sqlite:/var/lib/tpujob/store.db --listen 0.0.0.0:8475
"""

from __future__ import annotations

import argparse
import hmac
import json
import logging
import queue
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.serialize import decode, encode
from mpi_operator_tpu.opshell import metrics
from mpi_operator_tpu.machinery.store import (
    MODIFIED,
    AlreadyExists,
    BadPatch,
    Conflict,
    Forbidden,
    NotFound,
    NotLeader,
    QuotaExceeded,
    ReplicationUnavailable,
    TooManyRequests,
    Unauthorized,
    WatchEvent,
    patch_batch_via_loop,
)
from mpi_operator_tpu.machinery.yieldpoints import yield_point

log = logging.getLogger("tpujob.store")

_ERROR_CLASSES = {
    "NotFound": NotFound,
    "AlreadyExists": AlreadyExists,
    "Conflict": Conflict,
    "Unauthorized": Unauthorized,
    "Forbidden": Forbidden,
    "BadPatch": BadPatch,
    "NotLeader": NotLeader,
    "ReplicationUnavailable": ReplicationUnavailable,
    "TooManyRequests": TooManyRequests,
    "QuotaExceeded": QuotaExceeded,
}

# Store objects are manifests and status records — O(KB). The cap keeps an
# untrusted peer from driving a multi-GB allocation through Content-Length
# (same posture as tpucoll.cc's kMaxCount on the native wire).
_MAX_BODY_BYTES = 8 << 20

# Largest POST body the fair-queue tenant classifier will json.loads just
# to learn the namespace: a shed tenant's create must cost at most a
# bounded parse before its 429, never the full 8 MB one.
_TENANT_PARSE_CAP = 64 << 10


class _BodyTooLarge(Exception):
    """Content-Length rejected: too large, negative, or non-numeric."""

    def __init__(self, size):
        self.size = size
        super().__init__(f"body {size} bytes")


def read_token_file(path: Optional[str]) -> Optional[str]:
    """Load a shared bearer token from a file (whitespace-stripped).
    File-sourced so the secret never sits on a command line (≙ a mounted
    Secret, not a flag value visible in `ps`). An EMPTY file is an error,
    not 'no auth': a truncated/misconfigured Secret mount must fail closed —
    silently starting unauthenticated would be an invisible downgrade.
    'No auth' is expressed by not passing the flag at all."""
    if not path:
        return None
    with open(path) as f:
        tok = f.read().strip()
    if not tok:
        raise ValueError(
            f"token file {path!r} is empty; refusing to run unauthenticated "
            f"(omit the flag to disable auth)"
        )
    return tok


def read_agent_tokens_file(path: Optional[str]) -> Optional[Dict[str, str]]:
    """Per-agent scoped credentials (beyond the two shared tiers — the
    'agent-scoped would be better' half of the kube RBAC parity): a file of
    ``node-name:token`` lines. The holder of an agent token can read the
    cluster, register/heartbeat ITS OWN Node, and update pods bound to its
    node — nothing else. A compromised node can no longer delete other
    tenants' jobs or rebind work to itself. Fails closed on an empty or
    malformed file, and on duplicate tokens (ambiguous identity)."""
    if not path:
        return None
    out: Dict[str, str] = {}
    with open(path) as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            name, sep, tok = line.rpartition(":")
            if not sep or not name or not tok:
                raise ValueError(
                    f"{path}:{i}: expected 'node-name:token', got {line!r}"
                )
            if tok in out:
                raise ValueError(
                    f"{path}:{i}: token reused for {out[tok]!r} and "
                    f"{name!r} (identity must be unambiguous)"
                )
            out[tok] = name
    if not out:
        raise ValueError(
            f"agent tokens file {path!r} defines no tokens; refusing to run "
            f"(omit the flag to disable the agent tier)"
        )
    return out


def _force_requested(qs: Dict[str, List[str]]) -> bool:
    """THE force-flag parse, shared by the agent-tier authorization and the
    PUT handler: any drift between the two would turn a write authorized
    as non-force into a forced one (the same never-parse-differently rule
    as _route_parts)."""
    return qs.get("force", ["0"])[0] == "1"


def _route_parts(path: str) -> List[str]:
    """Decoded path segments of a request path (shared by routing and the
    agent-scope authorization so the two can never parse differently)."""
    parsed = urllib.parse.urlparse(path)
    return [urllib.parse.unquote(p) for p in parsed.path.split("/") if p]


def _is_peer_route(path: str) -> bool:
    """Replication RPC routes (peer-token tier; /v1/replica/status stays
    a public probe). ONE parse shared by auth, fair-queue gating, and
    dispatch, so the three can never classify a path differently."""
    parts = _route_parts(path)
    return (len(parts) == 3 and parts[:2] == ["v1", "replica"]
            and parts[2] != "status")


def check_bearer(header: str, tokens) -> Optional[str]:
    """THE bearer-token check (constant-time compare), shared by the store
    server and the agent's log endpoint so the two security checks can
    never drift. Returns the matching token from ``tokens`` (so callers can
    tier on identity), or None when the header is absent/malformed/wrong."""
    scheme, _, presented = header.partition(" ")
    presented = presented.strip()
    if scheme != "Bearer" or not presented:
        return None
    # compare BYTES: hmac.compare_digest raises TypeError on non-ASCII str
    # input, and a garbage header from a scanner must yield 401, not a
    # handler crash (500 on the store, dropped connection on the agent)
    presented_b = presented.encode("utf-8")
    for tok in tokens:
        if tok is not None and hmac.compare_digest(
            presented_b, tok.encode("utf-8")
        ):
            return tok
    return None


def _quote(part: str) -> str:
    """Path-segment-safe encoding for object names: Node names carry '/'
    (slice0/0x0) and must survive the /v1/objects/{kind}/{ns}/{name} route."""
    return urllib.parse.quote(part, safe="")


def parse_listen(spec: str) -> Tuple[str, int]:
    """'HOST:PORT', ':PORT', '[v6]:PORT', or bare 'PORT' → (host, port).
    Shared by every listen-address flag (--listen, --serve-store)."""
    host, sep, port = spec.rpartition(":")
    if not sep:
        host, port = "", spec
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    try:
        return host or "127.0.0.1", int(port)
    except ValueError:
        raise ValueError(
            f"invalid listen address {spec!r}; expected HOST:PORT"
        ) from None


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


# Watch fan-out encode accounting (the 10k-job round's O(events) proof):
# time spent turning committed events into response bytes, server-wide.
# With preencoding (the default) each event is JSON-encoded ONCE at append
# and every watcher's response is assembled by byte-joining the cached
# segments — growing watchers grows only the cheap join, so this clock is
# O(events). The legacy path (preencode=False, kept for the A/B bench)
# re-runs the wire-dict build + json.dumps per watcher per poll:
# O(watchers × events). bench_controlplane.py's fanout mode reads this.
_WATCH_ENCODE_LOCK = threading.Lock()
_WATCH_ENCODE_STATS = {
    "events_encoded": 0,   # json.dumps runs over event payloads
    "payloads": 0,         # watch response bodies produced
    "payload_bytes": 0,
    "encode_s": 0.0,       # wall time in json ENCODING of event data
    "assembly_s": 0.0,     # wall time byte-joining cached segments
}


def _note_watch_encode(dt: float, events: int = 0, payloads: int = 0,
                       nbytes: int = 0, assembly: bool = False) -> None:
    with _WATCH_ENCODE_LOCK:
        _WATCH_ENCODE_STATS["assembly_s" if assembly else "encode_s"] += dt
        _WATCH_ENCODE_STATS["events_encoded"] += events
        _WATCH_ENCODE_STATS["payloads"] += payloads
        _WATCH_ENCODE_STATS["payload_bytes"] += nbytes


def watch_encode_stats() -> Dict[str, Any]:
    """Snapshot of the server-side watch encode/delivery cost counters."""
    with _WATCH_ENCODE_LOCK:
        return dict(_WATCH_ENCODE_STATS)


def reset_watch_encode_stats() -> None:
    with _WATCH_ENCODE_LOCK:
        for k in _WATCH_ENCODE_STATS:
            _WATCH_ENCODE_STATS[k] = (
                0.0 if k in ("encode_s", "assembly_s") else 0
            )


class _Preencoded:
    """A response body assembled from cached byte segments (the watch
    fan-out hot path): ``_send`` writes it verbatim instead of running
    json.dumps over a payload dict every watcher already paid for once.
    Either a fully-formed ``body`` or ``(prefix, segments, suffix)`` to
    byte-join lazily (assembled once, cached)."""

    __slots__ = ("_body", "_prefix", "_segments", "_suffix")

    def __init__(self, body: Optional[bytes] = None,
                 prefix: bytes = b"", segments: Optional[List[bytes]] = None,
                 suffix: bytes = b""):
        self._body = body
        self._prefix = prefix
        self._segments = segments or []
        self._suffix = suffix

    def assemble(self) -> bytes:
        if self._body is None:
            t0 = time.perf_counter()
            self._body = self._prefix + b",".join(self._segments) + self._suffix
            _note_watch_encode(
                time.perf_counter() - t0, payloads=1,
                nbytes=len(self._body), assembly=True,
            )
        return self._body


class _RegistrationBarrier:
    """Sentinel pushed through the drain queue at watch registration: the
    backing store enqueues events in commit order, so once the drain thread
    reaches the sentinel, every event committed before registration is in
    the log and the head snapshot handed to the client excludes none of
    them (the async drain would otherwise assign them post-snapshot seqs
    and replay them). With a SqliteStore backing, writes from *other*
    processes reach the backing's watch queue only at its poll cadence —
    those may still replay within one poll interval; consumers are
    level-triggered, so replay is benign (same argument as relist)."""

    def __init__(self):
        self.reached = threading.Event()


class _EventLog:
    """Bounded event log with contiguous seqs and blocking reads.

    ≙ etcd's revision-indexed watch window: readers cursor by seq; a reader
    whose cursor fell off the retained window must relist — or, since every
    event also records the object's strictly-increasing resource_version,
    resume by rv (``resume_after_rv``) when the ring provably retains the
    full history past that rv.
    """

    def __init__(self, capacity: int = 4096, preencode: bool = True):
        self.capacity = capacity
        self.preencode = preencode
        self._cond = threading.Condition()
        # (seq, etype, kind, data, rv, origin, ts[, wire]): origin is the
        # writing span's (trace_id, span_id) or None, ts the commit time —
        # both ride the wire so a remote informer can link the work an
        # event causes back to the write that produced it
        # (machinery/trace.py). ``wire`` is the event's encoded wire BYTES,
        # computed once at append so fan-out to N watchers byte-joins
        # cached segments instead of re-running json.dumps N times
        # (O(events), not O(watchers×events) — preencode=False keeps the
        # legacy per-watcher path for the A/B bench).
        self._events: List[Tuple] = []
        self._next_seq = 1
        # rv completeness bounds for resume_after_rv: events with
        # rv <= _base_rv predate this server incarnation (unknown history);
        # _dropped_rv is the highest rv trimmed out of the ring. None base =
        # the backing store exposes no current_rv() → resume never provable.
        self._base_rv: Optional[int] = None
        self._dropped_rv = 0
        self._max_rv = 0

    def set_base_rv(self, rv: Optional[int]) -> None:
        with self._cond:
            self._base_rv = rv

    @property
    def head(self) -> int:
        """Seq of the newest appended event (0 if none)."""
        with self._cond:
            return self._next_seq - 1

    def watermark_rv(self) -> int:
        """Highest rv this incarnation can vouch for (base ∨ newest event)."""
        with self._cond:
            return max(self._base_rv or 0, self._max_rv)

    def append(self, etype: str, kind: str, data: Dict[str, Any],
               rv: int = 0, origin: Any = None, ts: float = 0.0) -> None:
        rest = None
        if self.preencode:
            # THE one json.dumps this event ever gets: every watcher's
            # long-poll response joins this cached segment by bytes. Run
            # OUTSIDE the condition lock (a large manifest's encode would
            # otherwise convoy every parked watch reader behind the write
            # path); only the seq — assigned under the lock — is spliced
            # in afterwards, a constant-cost bytes format.
            t0 = time.perf_counter()
            wire = _event_wire((0, etype, kind, data, rv, origin, ts))
            del wire["seq"]
            rest = json.dumps(wire).encode()[1:]  # '"type": ...}'
            _note_watch_encode(time.perf_counter() - t0, events=1)
        with self._cond:
            entry = (self._next_seq, etype, kind, data, rv, origin, ts)
            if rest is not None:
                entry = entry + (b'{"seq": %d, ' % self._next_seq + rest,)
            self._events.append(entry)
            self._next_seq += 1
            self._max_rv = max(self._max_rv, rv)
            if len(self._events) > self.capacity:
                drop = len(self._events) - self.capacity
                self._dropped_rv = max(
                    self._dropped_rv, max(e[4] for e in self._events[:drop])
                )
                del self._events[:drop]
            self._cond.notify_all()

    def resume_after_rv(self, rv: int) -> Optional[List[Tuple]]:
        """Events with object rv > ``rv``, oldest first — or None when the
        ring cannot PROVE it retains every such event (rv predates this
        incarnation's base, or needed events were trimmed): the caller must
        relist (the kube 410 Gone fallback). A complete empty replay is a
        valid resume (the client missed nothing)."""
        with self._cond:
            if self._base_rv is None or rv < self._base_rv:
                return None
            if rv < self._dropped_rv:
                return None
            if rv > max(self._base_rv, self._max_rv):
                # an anchor ABOVE everything this incarnation has vouched
                # for can only come from a different/reset rv space (e.g. a
                # restarted in-memory backing): treating it as an empty
                # resume would silently strand the client on its old-world
                # cache — relist instead
                return None
            return [e for e in self._events if e[4] > rv]

    def read_after(
        self, after: int, timeout: float
    ) -> Tuple[Optional[List[Tuple]], int]:
        """Events with seq > after, blocking up to ``timeout`` for the first.

        Returns (events, head). events is None when ``after`` predates the
        retained window (caller must relist).
        """
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                head = self._next_seq - 1
                if after > head:
                    # cursor from a previous server incarnation (the seq
                    # space reset on restart): the client can't know what it
                    # missed → relist
                    return None, head
                oldest_retained = self._next_seq - len(self._events)
                if after + 1 < oldest_retained and after < head:
                    return None, head  # gap: relist required
                out = [e for e in self._events if e[0] > after]
                if out:
                    return out, self._next_seq - 1
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], self._next_seq - 1
                self._cond.wait(remaining)


class StoreServer:
    """Serves a backing store's surface over HTTP (the etcd-equivalent)."""

    def __init__(self, backing: Any, host: str = "127.0.0.1", port: int = 0,
                 *, log_capacity: int = 4096, token: Optional[str] = None,
                 auth_reads: bool = False, read_token: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 agent_tokens: Optional[Dict[str, str]] = None,
                 preencode: bool = True,
                 fairness: Optional[Any] = None,
                 quota: Optional[Any] = None,
                 peer_token: Optional[str] = None):
        self.backing = backing
        # PEER tier: replication RPCs between replica-set members
        # (/v1/replica/* minus the public status probe). A dedicated
        # secret — replication traffic can rewrite history wholesale, so
        # neither the NODE nor the READ tier (nor even ADMIN: clients
        # mutate through the store verbs, never the replication seam) is
        # accepted there; see _peer_denied.
        self.peer_token = peer_token
        if peer_token is not None and not hasattr(backing, "append_entries"):
            raise ValueError(
                "peer_token configured but the backing store has no "
                "replication seam (append_entries); a peer tier that "
                "routes nowhere would silently advertise HA"
            )
        # APF-style per-tenant admission (machinery/fairqueue.FairQueue):
        # None = open admission (the pre-scale-out behavior). Watch
        # long-polls and probes bypass the seat gate (they park by design).
        self.fairness = fairness
        # namespace quota admission (fairqueue.NamespaceQuota): checked on
        # TPUJob creates, rejects with a typed 403 QuotaExceeded
        self.quota = quota
        # three token tiers (≙ kube RBAC: the aggregated edit-vs-view split
        # of /root/reference/manifests/base/cluster-role.yaml:96-151, plus
        # the node-scoped kubelet credential model):
        # `token` is the ADMIN tier — every route; `read_token` is the
        # READ-ONLY tier — GET routes only (watch included), mutations get
        # 403 Forbidden; `agent_tokens` (token → node name) is the NODE
        # tier — reads, its own Node, and pods bound to its node only (see
        # _agent_denied). Reads require a token only with auth_reads
        # (watches carry full object payloads).
        self.token = token
        self.read_token = read_token
        self.agent_tokens = agent_tokens or {}
        for tok, node in self.agent_tokens.items():
            # cross-tier reuse must fail closed at startup: check_bearer
            # matches the admin tier first, so an agent-tokens entry that
            # reuses the admin token would silently grant that node full
            # admin — the opposite of the scoped posture
            if tok in (token, read_token, peer_token):
                raise ValueError(
                    f"agent token for node {node!r} duplicates the "
                    f"admin/read/peer token; every tier needs a distinct "
                    f"secret"
                )
        if peer_token is not None and peer_token in (token, read_token):
            # a peer token misconfigured to the admin/read value would
            # grant that tier the replication seam (history rewrites)
            raise ValueError(
                "peer token duplicates the admin/read token; every tier "
                "needs a distinct secret"
            )
        if read_token is not None and read_token == token:
            # same fail-closed rule as the agent tier: check_bearer matches
            # the admin entry first, so a read token misconfigured to the
            # admin value would silently grant holders of the "read-only"
            # credential full mutation rights
            raise ValueError(
                "read token duplicates the admin token; every tier needs "
                "a distinct secret"
            )
        if token is None and (read_token is not None or auth_reads):
            # the CLIs guard this combination too, but an embedded caller
            # passing read_token/auth_reads without the anchoring admin
            # token would otherwise get a silently UNAUTHENTICATED server
            # (mutations included) — fail closed at construction
            raise ValueError(
                "read_token/auth_reads require the admin token "
                "(auth would otherwise be silently disabled)"
            )
        self.auth_reads = auth_reads
        # the seq space is per-incarnation; clients echo this id so a
        # restarted server (fresh seqs) can't be confused with the old one
        # even after the new log catches up past a stale cursor
        self.instance = uuid.uuid4().hex
        self._log = _EventLog(capacity=log_capacity, preencode=preencode)
        self._stop = threading.Event()
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # per-connection socket timeout: with deferred TLS handshakes
            # (below) a silent client occupies a handler thread until first
            # read; this bounds it. Must exceed the 55s watch long-poll cap.
            timeout = 65.0
            # TCP_NODELAY (consulted by StreamRequestHandler.setup, so it
            # must live on the HANDLER, not the server class): the response
            # is written as status/headers then body — with Nagle on, the
            # body segment waits on the peer's delayed ACK (tens of ms per
            # request), dwarfing the actual store work on every get/list
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, payload: Any) -> None:
                # preencoded-segments path (watch fan-out): the body is
                # byte-joined from per-event segments each encoded ONCE at
                # commit — this method must never re-serialize them
                if isinstance(payload, _Preencoded):
                    body = payload.assemble()
                else:
                    body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body_bytes(self) -> bytes:
                raw = self.headers.get("Content-Length", "0")
                try:
                    n = int(raw)
                except ValueError:
                    n = -1  # malformed header → same reject path
                if n < 0 or n > _MAX_BODY_BYTES:
                    # same posture as tpucoll's kMaxCount: a peer must not
                    # drive an arbitrary allocation (or an
                    # rfile.read(-1)-to-EOF stall) through a length field
                    raise _BodyTooLarge(raw)
                return self.rfile.read(n) if n else b""

            def _auth_error(
                self, method: str, body
            ) -> Optional[Tuple[int, str]]:
                """None when allowed; else (401, msg) for a bad/absent
                token or (403, msg) for a valid token outside its scope.
                ``body`` is a CALLABLE returning the parsed body — only the
                agent tier (already authenticated) ever parses it, so
                anonymous peers cannot drive json.loads CPU. Stashes the
                matched token tier on the handler (``self._tier``) so the
                fair-queue tenant classification reuses it instead of
                re-running the O(tokens) constant-time scan — at a
                1k-entry agent-tokens file that second scan would double
                the auth cost of every admitted request."""
                self._tier = None
                if _is_peer_route(self.path):
                    # BEFORE the open-server early-out: peer replication
                    # routes fail closed even on an otherwise
                    # unauthenticated store — anyone who can dial the
                    # port must not be able to rewrite replicated history
                    return server._peer_denied(
                        self.headers.get("Authorization", "")
                    )
                if server.token is None and not server.agent_tokens:
                    return None
                if method == "GET" and _route_parts(self.path) in (
                    ["healthz"], ["v1", "replica", "status"]
                ):
                    # liveness and role probes carry no headers; /healthz
                    # leaks nothing and /v1/replica/status is how `ctl
                    # store status` and failover triage discover
                    # membership without the tenant token, so both stay
                    # open even under --auth-reads (authz_policy.json
                    # declares this posture explicitly)
                    return None
                candidates = (server.token, server.read_token,
                              *server.agent_tokens)
                matched = check_bearer(
                    self.headers.get("Authorization", ""), candidates
                )
                # identity, not equality: check_bearer returns the exact
                # object from the tuple, so tiering is not a string compare
                is_admin = matched is server.token and matched is not None
                is_read = matched is server.read_token and matched is not None
                agent_node = (
                    server.agent_tokens.get(matched)
                    if matched is not None and not (is_admin or is_read)
                    else None
                )
                if is_admin:
                    self._tier = "admin"
                elif is_read:
                    self._tier = "read"
                elif agent_node is not None:
                    self._tier = ("node", agent_node)
                if method == "GET":
                    if not server.auth_reads:
                        return None
                    if is_admin or is_read or agent_node is not None:
                        return None
                    return (401, "missing or invalid bearer token "
                                 "(server runs with --token-file)")
                if is_admin:
                    return None
                if is_read:
                    return (403, "the read-only token cannot mutate "
                                 "(server runs with --read-token-file)")
                if agent_node is not None:
                    return server._agent_denied(
                        method, self.path, body(), agent_node
                    )
                return (401, "missing or invalid bearer token "
                             "(server runs with --token-file)")

            def _dispatch(self, method: str) -> None:
                try:
                    # DRAIN the body for EVERY method before anything else:
                    # an unread body on a keep-alive connection desyncs
                    # framing — a bodied DELETE/GET would smuggle its body
                    # bytes as the next request (classic request smuggling
                    # behind a connection-reusing proxy). Drained but NOT
                    # parsed: json.loads on 8 MB of pathological input must
                    # not be reachable pre-authentication.
                    raw = self._body_bytes()
                    cache: Dict[str, Any] = {}

                    def body() -> Dict[str, Any]:
                        if "v" not in cache:
                            cache["v"] = json.loads(raw) if raw else {}
                        return cache["v"]

                    denied = self._auth_error(method, body)
                    if denied is not None:
                        code, msg = denied
                        self._send(code, {
                            # 409 Conflict: agent-tier writes whose stale rv
                            # would race a concurrent operator write are
                            # bounced BEFORE authz can be gamed — the client
                            # surfaces it as Conflict so optimistic retry
                            # loops re-read instead of aborting
                            "error": {403: "Forbidden", 409: "Conflict"}.get(
                                code, "Unauthorized"),
                            "message": msg,
                        })
                        return
                    seat = None
                    if server.fairness is not None:
                        # APF admission AFTER authn (the tenant identity is
                        # trustworthy) and BEFORE any backing-store work:
                        # over-limit tenants are shed here at bounded cost.
                        # Classification parses a POST body only below
                        # _TENANT_PARSE_CAP — an 8 MB create from an
                        # already-shed tenant must not buy a full
                        # json.loads before its 429 (oversized bodies
                        # classify by token tier instead).
                        try:
                            cls_body = (
                                body()
                                if method == "POST"
                                and len(raw) <= _TENANT_PARSE_CAP
                                else None
                            )
                            tenant = server._tenant_of(
                                method, self.path,
                                body=cls_body,
                                tier=self._tier,
                            )
                            if server._fair_gated(method, self.path):
                                seat = server.fairness.admit(
                                    tenant,
                                    level=server._level_of(
                                        self.path, cls_body
                                    ),
                                )
                            elif _route_parts(self.path) == ["v1", "watch"]:
                                # long-polls skip the seat pool (they park
                                # by design) but a reconnect/relist storm
                                # still drains the tenant's token bucket
                                server.fairness.throttle(tenant)
                        except TooManyRequests as e:
                            self._send(429, {
                                "error": "TooManyRequests",
                                "message": str(e),
                            })
                            return
                    try:
                        code, payload = server._handle_traced(
                            method, self.path,
                            self.headers.get(trace.TRACEPARENT_HEADER, ""),
                            body() if method in ("POST", "PUT", "PATCH")
                            else {},
                        )
                    finally:
                        if seat is not None:
                            seat.__exit__(None, None, None)
                    self._send(code, payload)
                except json.JSONDecodeError as e:
                    # malformed body from an (authenticated) peer: a 400,
                    # not an opaque 500
                    self._send(400, {
                        "error": "BadRequest",
                        "message": f"body is not valid JSON: {e}",
                    })
                except _BodyTooLarge as e:
                    # the unread body would desync keep-alive framing: close
                    self.close_connection = True
                    try:
                        self._send(413, {
                            "error": "BadRequest",
                            "message": f"Content-Length {e.size!r} rejected "
                                       f"(cap {_MAX_BODY_BYTES} bytes)",
                        })
                    # oplint: disable=EXC001 — best-effort reject to a peer
                    # that is gone; scanner noise must not reach the log
                    except Exception:
                        pass
                except BrokenPipeError:
                    pass
                except Exception as e:  # surface, don't kill the thread
                    log.debug("request handler error", exc_info=True)
                    try:
                        self._send(500, {"error": "Internal", "message": str(e)})
                    # oplint: disable=EXC001 — the 500 above is the
                    # surfacing; this guard only covers a vanished peer
                    except Exception:
                        pass

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_PATCH(self):
                self._dispatch("PATCH")

            def do_DELETE(self):
                self._dispatch("DELETE")

        class QuietThreadingHTTPServer(ThreadingHTTPServer):
            # listen(2) backlog: socketserver's default of 5 silently
            # RSTs concurrent connects the moment a fleet of agents (or a
            # watcher herd re-polling after a sever) dials in together —
            # at 1k hollow nodes the scale bench hit exactly this. 512 ≙
            # the order kube-apiserver serves; the kernel clamps to
            # net.core.somaxconn anyway.
            request_queue_size = 512

            def handle_error(self, request, client_address):
                # port scanners / plain-HTTP probes against a TLS listener
                # fail their deferred handshake in the handler thread; one
                # bad connection is not worth a stderr traceback
                log.debug(
                    "connection error from %s", client_address, exc_info=True
                )

        # bind first — it is the only fallible step; registering the backing
        # watch before a failed bind would leak a never-drained queue that
        # the backing store fills forever (retry-on-EADDRINUSE loops)
        self._httpd = QuietThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        # TLS on the seam (≙ kube-apiserver serving TLS): without it the
        # bearer tokens and all job state — including the pod commands
        # agents will execute — cross the cluster network sniffable.
        # Self-signed is acceptable; clients pin the cert via --tls-ca-file.
        self.tls = bool(tls_cert)
        if tls_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key or None)
            # handshake DEFERRED off the accept thread: with the default
            # do_handshake_on_connect=True the handshake runs inside
            # accept() in the single serve_forever thread, so one silent
            # client (half-open connection, slowloris, `nc store PORT`)
            # would freeze the whole control plane. Deferred, it runs on
            # first read in the per-connection handler thread, bounded by
            # the Handler.timeout above.
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False,
            )
        self.host, self.port = self._httpd.server_address[:2]
        # histogram label naming the backing class (verb×backend store
        # request latency: SqliteStore vs ObjectStore vs ReplicaClient)
        self._backend_label = type(backing).__name__
        # request counters (read by bench_controlplane.py to measure the
        # store-side read load informer caches remove); plain dict under a
        # lock — snapshot with stats()
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, int] = {
            "get": 0, "list": 0, "watch": 0,
            "create": 0, "update": 0, "delete": 0, "relist": 0,
            "patch": 0, "patch_batch": 0, "patch_item": 0, "conflict": 0,
        }
        self._watch_q = backing.watch(None)
        # rv anchor: everything at or below the backing's CURRENT rv is
        # outside this incarnation's event ring, so ?resource_version=
        # resume is provable only above it (registered-watch events all
        # land later). Backings without current_rv() never prove resume.
        current_rv = getattr(backing, "current_rv", None)
        self._log.set_base_rv(current_rv() if callable(current_rv) else None)
        self._drain = threading.Thread(
            target=self._drain_loop, name="http-store-drain", daemon=True
        )
        self._serve = threading.Thread(
            # tight shutdown poll: serve_forever's default 0.5s poll makes
            # every stop() block half a second — felt by each failover
            # restart and by harnesses (storecheck ddmin) that cycle
            # hundreds of servers
            target=lambda: self._httpd.serve_forever(poll_interval=0.05),
            name="http-store-serve", daemon=True,
        )

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "StoreServer":
        self._drain.start()
        self._serve.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.backing.stop_watch(self._watch_q)
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        host = f"[{self.host}]" if ":" in self.host else self.host
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{host}:{self.port}"

    def _drain_loop(self) -> None:
        while not self._stop.is_set():
            try:
                ev = self._watch_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if isinstance(ev, _RegistrationBarrier):
                ev.reached.set()
                continue
            self._log.append(
                ev.type, ev.kind, encode(ev.obj),
                ev.obj.metadata.resource_version or 0,
                getattr(ev, "trace", None), getattr(ev, "ts", 0.0),
            )

    # verbs that mirror into the tpu_operator_store_write_requests_total
    # counter (patch_batch = the batch request, patch_item = its items)
    _WRITE_VERBS = ("create", "update", "delete", "patch", "patch_batch",
                    "patch_item")

    def stats(self) -> Dict[str, int]:
        """Snapshot of per-route request counters (reads: get/list/watch;
        writes: create/update/delete/patch/patch_batch; relist = full-state
        recoveries served; conflict = optimistic 409s bounced)."""
        with self._stats_lock:
            return dict(self._stats)

    def _count(self, what: str) -> None:
        with self._stats_lock:
            self._stats[what] = self._stats.get(what, 0) + 1
        if what in self._WRITE_VERBS:
            metrics.store_write_requests.inc(verb=what)
        elif what == "conflict":
            metrics.store_write_conflicts.inc()

    # -- fair-queuing admission (APF) ---------------------------------------

    @staticmethod
    def _fair_gated(method: str, path: str) -> bool:
        """Routes the fair queue's concurrency seats apply to: everything
        except watch long-polls (they PARK by design — seat-gating them
        would let one tenant's idle watchers consume the whole pool) and
        the healthz/replica-status probes (liveness must not queue behind
        tenant load — a starved probe reads as a dead store)."""
        parts = _route_parts(path)
        if parts == ["healthz"] or parts[:2] == ["v1", "replica"]:
            # replica routes cover the status probe AND the peer RPCs:
            # replication is system-plane traffic — a ship queued behind
            # a tenant's seat wait would add tenant latency to EVERY
            # write's majority ack (and could deadlock a leader whose
            # own seat pool is saturated by the tenants it serves)
            return False
        if parts == ["v1", "watch"] and method == "GET":
            return False
        return True

    @staticmethod
    def _level_of(path: str,
                  body: Optional[Dict[str, Any]] = None) -> int:
        """Priority LEVEL within a tenant's seat (fairqueue.LEVEL_*):
        TPUServe routes — the serving control plane, whose write latency
        is user-facing — classify as serve; everything else is batch.
        ``body`` is the already-parsed (size-capped) POST body the tenant
        classifier produced; a create's kind rides it."""
        from mpi_operator_tpu.machinery.fairqueue import (
            LEVEL_BATCH,
            LEVEL_SERVE,
        )

        parts = _route_parts(path)
        if parts[:3] == ["v1", "objects", "TPUServe"]:
            return LEVEL_SERVE
        if parts == ["v1", "objects"] and isinstance(body, dict) \
                and body.get("kind") == "TPUServe":
            return LEVEL_SERVE
        return LEVEL_BATCH

    def _tenant_of(self, method: str, path: str,
                   auth_header: Optional[str] = None,
                   body: Optional[Dict[str, Any]] = None,
                   tier: Any = None) -> str:
        """Classify a request to its fairness tenant: the NAMESPACE for
        object routes (the natural multi-tenancy boundary — one team's
        list storm is that team's tenant; creates carry it in the body),
        the token tier otherwise (``node:<name>`` for agent credentials,
        ``admin``/``read`` for the shared tiers, ``anon`` for
        unauthenticated traffic). Agent tokens classify by node identity
        even on object routes so a misbehaving node cannot launder load
        through its pods' namespaces. ``tier`` is the identity
        ``_auth_error`` already matched ("admin"/"read"/("node", name)/
        None) — pass ``auth_header`` instead only where no prior auth ran
        (direct callers, tests)."""
        if tier is None and auth_header:
            matched = check_bearer(
                auth_header,
                (self.token, self.read_token, *self.agent_tokens),
            )
            if matched is self.token and matched is not None:
                tier = "admin"
            elif matched is self.read_token and matched is not None:
                tier = "read"
            elif matched is not None:
                tier = ("node", self.agent_tokens[matched])
        if isinstance(tier, tuple):
            return f"node:{tier[1]}"
        if tier == "admin":
            # system traffic outranks namespace attribution (≙ kube APF's
            # exempt system flow schemas): the controller's writes INTO a
            # noisy tenant's namespace must not land in that tenant's
            # bucket, or the tenant's own client could rate-starve its
            # jobs' reconciliation
            return "admin"
        parts = _route_parts(path)
        if parts[:2] == ["v1", "objects"] and len(parts) >= 4:
            return f"ns:{parts[3]}"
        if parts[:2] == ["v1", "objects"] and len(parts) == 3:
            qs = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
            ns = qs.get("namespace", [None])[0]
            if ns:
                return f"ns:{ns}"
        if parts == ["v1", "objects"] and method == "POST" and body:
            obj = body.get("object")
            meta = obj.get("metadata") if isinstance(obj, dict) else None
            ns = meta.get("namespace") if isinstance(meta, dict) else None
            if ns:
                return f"ns:{ns}"
        if tier == "read":
            return "read"
        return "anon"

    # -- authorization ------------------------------------------------------

    def _peer_denied(self, header: str) -> Optional[Tuple[int, str]]:
        """The PEER tier's gate: replication RPCs accept EXACTLY the peer
        token. The split matches the repo-wide 401-vs-403 pin in
        authz_policy.json: a MISSING or UNRECOGNIZED token is a 401
        (authentication failed — present a credential), while a VALID
        token from another tier (admin, read, node — none of them is a
        replication identity) is a 403 (authenticated, but out of scope);
        with no peer token configured the routes are disabled outright as
        a 403 regardless of header. Always fail closed: replication
        traffic rewrites history."""
        if self.peer_token is None:
            return (403, "replica peer routes are disabled on this "
                         "server (run with --peer-token-file)")
        if check_bearer(header, (self.peer_token,)) is not None:
            return None
        if check_bearer(
            header, (self.token, self.read_token, *self.agent_tokens)
        ) is not None:
            return (403, "replica peer routes require the peer token "
                         "(the admin/read/node tiers are not replication "
                         "identities)")
        return (401, "missing or invalid bearer token "
                     "(server runs with --peer-token-file)")

    def _agent_denied(
        self, method: str, path: str, body: Dict[str, Any], node: str
    ) -> Optional[Tuple[int, str]]:
        """The NODE tier's scope (≙ the kubelet's node-restricted
        credential): reads everywhere; create/update ITS OWN Node; update
        pods CURRENTLY bound to its node (without rebinding, relabeling, or
        re-uid-ing them). None when allowed, else ``(status, message)`` —
        403 for out-of-scope, 409 for stale-rv writes that must retry. The
        current binding is checked against the BACKING store, not the
        submitted object — a compromised agent must not claim another
        node's pod by writing its own name into spec.node_name."""
        from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE

        parts = _route_parts(path)
        obj = body.get("object") if isinstance(body, dict) else None
        obj = obj if isinstance(obj, dict) else {}
        meta = obj.get("metadata")
        meta = meta if isinstance(meta, dict) else {}
        if method == "POST" and parts == ["v1", "patch-batch"]:
            items = body.get("items") if isinstance(body, dict) else None
            if not isinstance(items, list):
                return None  # malformed: the handler 400s it for every tier
            for it in items:
                it = it if isinstance(it, dict) else {}
                denied = self._agent_patch_denied(
                    [str(it.get("kind", "")), str(it.get("namespace", "")),
                     str(it.get("name", "")),
                     str(it.get("subresource") or "")],
                    it.get("patch"), node,
                )
                if denied is not None:
                    return denied  # one out-of-scope item fails the batch
            return None
        if (
            method == "PATCH"
            and len(parts) in (5, 6)
            and parts[:2] == ["v1", "objects"]
        ):
            rest = parts[2:] + ([""] if len(parts) == 5 else [])
            return self._agent_patch_denied(
                rest, body.get("patch") if isinstance(body, dict) else None,
                node,
            )
        if method == "POST" and parts == ["v1", "objects"]:
            if (
                body.get("kind") == "Node"
                and meta.get("namespace") == NODE_NAMESPACE
                and meta.get("name") == node
            ):
                return None  # its own registration
            return (403,
                    f"agent {node!r} may only create its own Node object, "
                    f"not {body.get('kind')}/{meta.get('name')}")
        if (
            method == "PUT"
            and len(parts) == 5
            and parts[:2] == ["v1", "objects"]
        ):
            qs = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
            if _force_requested(qs):
                # force bypasses optimistic concurrency: a compromised
                # agent could clobber a concurrent rebind/eviction/reaper
                # write without a Conflict ever surfacing. The real agent
                # uses optimistic conflict-retry everywhere.
                return (403,
                        f"agent {node!r} may not force-update (optimistic "
                        f"writes only — retry on Conflict)")
            kind, ns, name = parts[2:]
            if kind == "Node":
                if ns != NODE_NAMESPACE or name != node:
                    return 403, f"agent {node!r} may only update its own Node"
                status = obj.get("status")
                status = status if isinstance(status, dict) else {}
                try:
                    stored = self.backing.get("Node", ns, name)
                    cordoned = stored.status.unschedulable
                    stored_rv = stored.metadata.resource_version
                except KeyError:
                    return None  # authz before existence; backing 404s it
                submitted_rv = (obj.get("metadata") or {}).get(
                    "resource_version"
                )
                if submitted_rv != stored_rv:
                    # stale (or predicted-future) rv: bounce with Conflict
                    # BEFORE the scope checks below. The old rule denied a
                    # cordon flip only when submitted rv == stored rv, which
                    # was TOCTOU-racy: an agent could submit a future rv
                    # (mismatch at authz → allowed) while a concurrent benign
                    # heartbeat advanced the node to exactly that rv, landing
                    # the un-cordon. Conflict preserves the benign agent's
                    # optimistic retry loop (re-read, preserve the flag,
                    # retry) where a 403 would abort it.
                    return (409,
                            f"Node {ns}/{name}: resource_version "
                            f"{submitted_rv} != {stored_rv}")
                if bool(status.get("unschedulable", False)) != bool(cordoned):
                    # the cordon flag belongs to the OPERATOR (`ctl
                    # cordon/drain` is containment against exactly a
                    # compromised node): an agent un-cordoning itself would
                    # pull other tenants' gangs back onto it
                    return (403,
                            f"agent {node!r} may not change its own "
                            f"cordon flag (status.unschedulable)")
                stored_conds = [c.to_dict() for c in stored.status.conditions]
                if (status.get("conditions") or []) != stored_conds:
                    # Node conditions (the Draining state machine) are
                    # operator-owned, same argument as the cordon flag —
                    # a full-object PUT at matching rv must carry them
                    # through unchanged
                    return (403,
                            f"agent {node!r} may not change its own "
                            f"status.conditions (operator-owned)")
                return None  # its own heartbeat
            if kind == "Pod":
                spec = obj.get("spec")
                spec = spec if isinstance(spec, dict) else {}
                if (
                    meta.get("name", name) != name
                    or meta.get("namespace", ns) != ns
                ):
                    # body identity disagrees with the URL: the handler's
                    # URL/body integrity wall 400s this for every tier —
                    # fall through so the response stays a BadRequest, not
                    # a misleading scope denial
                    return None
                try:
                    cur = self.backing.get("Pod", ns, name)
                except KeyError:
                    return (403,
                            f"agent {node!r} may only update pods bound to "
                            f"its node (pod {ns}/{name} is bound to None)")
                bound_to = cur.spec.node_name
                if bound_to != node or spec.get("node_name") != node:
                    return (403,
                            f"agent {node!r} may only update pods bound to "
                            f"its node (pod {ns}/{name} is bound to "
                            f"{bound_to!r})")
                # identity pinning: labels and uid are controller-owned. An
                # agent that could relabel a pod (LABEL_JOB_NAME) would
                # inject it into another job's worker set — controller and
                # scheduler group pods purely by that label — triggering
                # spurious gang restarts or permanently failing another
                # tenant's job. Same for uid: the eviction/phase guards key
                # incarnations off it.
                if meta.get("uid", cur.metadata.uid) != cur.metadata.uid:
                    return (403,
                            f"agent {node!r} may not change metadata.uid "
                            f"of pod {ns}/{name}")
                if (meta.get("labels") or {}) != (cur.metadata.labels or {}):
                    return (403,
                            f"agent {node!r} may not change metadata.labels "
                            f"of pod {ns}/{name} (labels are "
                            f"controller-owned identity)")
                return None  # status mirror / eviction of its own pod
        return 403, f"agent {node!r} may not {method} this route"

    def _agent_patch_denied(
        self, rest: List[str], patch: Any, node: str
    ) -> Optional[Tuple[int, str]]:
        """The NODE tier's PATCH scope — strictly TIGHTER than its PUT
        scope: **status subresource only** (spec/metadata are frozen by the
        store itself, so a compromised agent physically cannot rebind,
        relabel or re-uid anything through this verb), on its own Node
        (minus the cordon flag) and on pods currently bound to it. ``rest``
        is [kind, namespace, name, subresource]; None = allowed."""
        from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE

        if len(rest) != 4:
            return 403, f"agent {node!r} may not PATCH this route"
        kind, ns, name, subresource = rest
        if subresource != "status":
            return (403,
                    f"agent {node!r} is granted patch-status-only "
                    f"(use the /status subresource)")
        patch = patch if isinstance(patch, dict) else {}
        status = patch.get("status")
        status = status if isinstance(status, dict) else {}
        if kind == "Node":
            if ns != NODE_NAMESPACE or name != node:
                return 403, f"agent {node!r} may only patch its own Node"
            if "unschedulable" in status:
                # the cordon flag belongs to the OPERATOR; rejecting the
                # KEY outright (not just value flips) keeps the check
                # TOCTOU-free — there is no stored state to race against,
                # and a heartbeat has no reason to mention the flag
                return (403,
                        f"agent {node!r} may not touch "
                        f"status.unschedulable (cordon is operator-owned)")
            if "conditions" in status:
                # same posture for Node conditions: the Draining state
                # machine is the DrainController's — a compromised node
                # clearing its own Draining condition could lure the
                # drain plane into declaring a half-evacuated node done
                return (403,
                        f"agent {node!r} may not touch status.conditions "
                        f"(the Draining state machine is operator-owned)")
            return None  # its own heartbeat
        if kind == "Pod":
            try:
                cur = self.backing.get("Pod", ns, name)
            except KeyError:
                # pod already deleted (gang cleanup racing the agent's
                # flush): ALLOW, and let the handler produce the per-item
                # NotFound the agent expects in-band — a 403 here would
                # fail the whole batch, heartbeat included, and the agent
                # would requeue the dead pod's mirror and 403 on every
                # subsequent tick until the monitor declared it lost.
                # Pin "absent" so a pod recreated (possibly bound to
                # another tenant's node) between this check and the apply
                # can NEVER be hit: the impossible uid precondition turns
                # such a race into an in-band Conflict.
                self._pin_uid(patch, "")
                return None
            if cur.spec.node_name != node:
                return (403,
                        f"agent {node!r} may only patch pods bound to its "
                        f"node (pod {ns}/{name} is bound to "
                        f"{cur.spec.node_name!r})")
            # apply-time scope enforcement: pin the patch to the EXACT
            # incarnation whose binding was just verified — the store's
            # uid precondition is checked atomically with the merge, so
            # the authz-to-apply window (delete + recreate, batch items
            # applying one by one) cannot be exploited to write a pod
            # this agent does not own
            self._pin_uid(patch, cur.metadata.uid)
            return None  # status mirror of its own pod
        return 403, f"agent {node!r} may not patch {kind} objects"

    @staticmethod
    def _pin_uid(patch: Any, uid: str) -> None:
        """Inject a uid precondition into an (in-place shared) patch dict:
        the handler applies the SAME object _auth_error inspected, so the
        pin travels with the request. Overwrites any client-supplied uid —
        the server-observed incarnation is authoritative for scope. A
        malformed patch (non-dict, non-dict metadata) is left alone; the
        backing rejects it with BadPatch anyway."""
        if not isinstance(patch, dict):
            return
        meta = patch.get("metadata")
        if meta is None:
            patch["metadata"] = {"uid": uid}
        elif isinstance(meta, dict):
            meta["uid"] = uid

    # -- request handling ---------------------------------------------------

    # routes whose latency lands in the store-request histogram (watch
    # long-polls park by design — 25s of wait is not 25s of work — and
    # healthz/replica-status are probes, not store traffic)
    _TIMED_VERBS = ("create", "get", "list", "update", "delete", "patch",
                    "patch_batch")

    @staticmethod
    def _route_verb(method: str, path: str) -> Optional[str]:
        """The store verb a request resolves to (same _route_parts parse
        the router uses, so the two can never disagree); None = untimed."""
        parts = _route_parts(path)
        if parts == ["v1", "patch-batch"] and method == "POST":
            return "patch_batch"
        if parts[:2] == ["v1", "objects"]:
            if method == "POST":
                return "create"
            if method == "GET":
                return "list" if len(parts) == 3 else "get"
            if method == "PUT":
                return "update"
            if method == "DELETE":
                return "delete"
            if method == "PATCH":
                return "patch"
        return None

    def _handle_traced(
        self, method: str, path: str, traceparent: str, body: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        """Dispatch wrapper adding the server-side observability: a
        ``store.request`` span (parented on the client's traceparent when
        one rode in — the cross-process hop) held current across the
        backing call, so the backing's watch event captures THIS span as
        the write's origin; the request latency lands in the verb×backend
        histogram where the span closes."""
        if _is_peer_route(path):
            if method != "POST":
                return 404, {"error": "NotFound",
                             "message": "replica peer routes are POST"}
            return self._handle_replica(
                _route_parts(path)[2], body, traceparent,
            )
        verb = self._route_verb(method, path)
        if verb is None:
            return self._handle(method, path, body)
        parent = trace.parse_traceparent(traceparent)
        t0 = time.perf_counter()
        with trace.start_span(
            "store.request", parent=parent,
            attrs={"verb": verb, "backend": self._backend_label},
        ) as sp:
            code, payload = self._handle(method, path, body)
            if code >= 400:
                sp.set_attr("status", code)
        metrics.store_request_latency.observe(
            time.perf_counter() - t0,
            verb=verb, backend=self._backend_label,
        )
        return code, payload

    # peer RPC route → the ReplicaNode handler it dispatches to (the
    # whole deployed replication protocol, ISSUE 12). Epoch fencing runs
    # server-side IN the handler — StaleEpoch crosses back as a typed
    # 409 the peer fabric re-raises, so fencing is transport-agnostic.
    _PEER_ROUTE_METHODS = {
        "request-vote": "request_vote",
        "append-entries": "append_entries",
        "fetch-entries": "fetch_entries",
        "install-snapshot": "install_snapshot",
        "snapshot-chunk": "snapshot_chunk",
        "snapshot-done": "snapshot_done",
    }

    def _handle_replica(
        self, route: str, body: Dict[str, Any], traceparent: str
    ) -> Tuple[int, Dict[str, Any]]:
        """Dispatch one peer replication RPC into the backing replica
        node (auth already passed the peer gate in _auth_error). The
        server-side span parents on the caller's traceparent, so a
        shipped write's apply on the follower lands in the WRITE's trace
        — the anchor a later election links through (`ctl trace
        --last-incident` failover continuity)."""
        from mpi_operator_tpu.machinery.replicated_store import (
            PeerUnreachable,
            StaleEpoch,
            UnknownTransfer,
        )

        meth = self._PEER_ROUTE_METHODS.get(route)
        fn = getattr(self.backing, meth, None) if meth else None
        if fn is None:
            return 404, {"error": "NotFound",
                         "message": f"no replica route {route!r}"}
        args = body.get("args")
        if not isinstance(args, list):
            return 400, {"error": "BadRequest",
                         "message": "peer RPC body needs an args list"}
        parent = trace.parse_traceparent(traceparent)
        try:
            with trace.start_span(
                "replica." + meth, parent=parent,
                attrs={"src": str(body.get("src", "?"))},
            ):
                return 200, {"result": fn(*args)}
        except StaleEpoch as e:
            return 409, {"error": "StaleEpoch",
                         "epoch": e.current_epoch, "message": str(e)}
        except UnknownTransfer as e:
            return 404, {"error": "UnknownTransfer", "message": str(e)}
        except PeerUnreachable as e:
            return 503, {"error": "PeerUnreachable", "message": str(e)}
        except TypeError as e:
            # malformed args from a skewed peer: a 400, not a 500
            return 400, {"error": "BadRequest", "message": str(e)}

    def _handle(
        self, method: str, path: str, body: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        parsed = urllib.parse.urlparse(path)
        qs = urllib.parse.parse_qs(parsed.query)
        # unquote AFTER splitting: %2F inside an object name must not create
        # path segments (Node names are slice0/0x0) — _route_parts does this
        parts = _route_parts(path)
        try:
            if parts == ["healthz"]:
                return 200, {"ok": True}
            if parts == ["v1", "replica", "status"] and method == "GET":
                # replica-role introspection (`ctl store status`); a
                # non-replicated backing is an honest standalone
                status_fn = getattr(self.backing, "replica_status", None)
                if callable(status_fn):
                    return 200, dict(status_fn(), endpoint=self.url)
                return 200, {"role": "standalone", "endpoint": self.url}
            if parts == ["v1", "watch"] and method == "GET":
                return self._handle_watch(qs)
            if parts == ["v1", "patch-batch"] and method == "POST":
                return self._handle_patch_batch(body)
            if parts[:2] == ["v1", "objects"]:
                return self._handle_objects(method, parts[2:], qs, body)
            return 404, {"error": "NotFound", "message": f"no route {parsed.path}"}
        except NotFound as e:
            return 404, {"error": "NotFound", "message": str(e)}
        except AlreadyExists as e:
            return 409, {"error": "AlreadyExists", "message": str(e)}
        except Conflict as e:
            self._count("conflict")
            return 409, {"error": "Conflict", "message": str(e)}
        except NotLeader as e:
            # 421 Misdirected Request: this replica cannot serve the
            # mutation; the payload carries the leader hint the client's
            # failover path follows before backing off
            return 421, {"error": "NotLeader", "message": str(e),
                         "leader": e.leader}
        except ReplicationUnavailable as e:
            # 503: the write's outcome is INDETERMINATE (committed on a
            # minority) — never retried automatically by the client, which
            # must surface it so the caller can re-read first
            return 503, {"error": "ReplicationUnavailable",
                         "message": str(e)}
        except QuotaExceeded as e:
            # BEFORE the subsumed classes: a typed quota denial carries the
            # actionable "raise the quota or free capacity" message
            return 403, {"error": "QuotaExceeded", "message": str(e)}
        except TooManyRequests as e:
            # a backing store may load-shed too (a replica proxying to a
            # fair-queued leader): surface, never mask as a 500
            return 429, {"error": "TooManyRequests", "message": str(e)}
        except BadPatch as e:
            return 400, {"error": "BadPatch", "message": str(e)}
        except KeyError as e:  # unknown kind from serialize registry
            return 400, {"error": "BadRequest", "message": str(e)}

    def _handle_objects(
        self,
        method: str,
        rest: List[str],
        qs: Dict[str, List[str]],
        body: Dict[str, Any],
    ) -> Tuple[int, Dict[str, Any]]:
        if method == "POST" and not rest:
            obj = decode(body["kind"], body["object"])
            if self.quota is not None:
                # namespace quota admission (the reference's ResourceQuota
                # layer): a typed 403 BEFORE the create hits the backing
                self.quota.check_create(self.backing, obj)
            self._count("create")
            created = self.backing.create(obj)
            return 200, {"object": encode(created)}
        if method == "GET" and len(rest) == 1:
            self._count("list")
            kind = rest[0]
            namespace = qs.get("namespace", [None])[0]
            selector = None
            if "selector" in qs:
                # JSON on the wire: label values may contain ','/'=' and the
                # duck-typed list() contract must match the other backends
                try:
                    selector = json.loads(qs["selector"][0])
                except json.JSONDecodeError:
                    selector = None
                if not isinstance(selector, dict):
                    return 400, {
                        "error": "BadRequest",
                        "message": "selector must be a JSON object "
                                   "(version-skewed client?)",
                    }
            objs = self.backing.list(kind, namespace, selector)
            return 200, {"objects": [encode(o) for o in objs]}
        if len(rest) == 3:
            kind, namespace, name = rest
            if method == "GET":
                self._count("get")
                return 200, {"object": encode(self.backing.get(kind, namespace, name))}
            if method == "PUT":
                obj = decode(kind, body["object"])
                if (
                    obj.kind != kind
                    or obj.metadata.namespace != namespace
                    or obj.metadata.name != name
                ):
                    # the URL is what authorization was decided on; the
                    # backing update keys off the BODY's identity — letting
                    # them disagree would turn every scope check into a
                    # bypass (authorize against pod A, overwrite pod B)
                    return 400, {
                        "error": "BadRequest",
                        "message": (
                            f"URL names {kind}/{namespace}/{name} but the "
                            f"body object is {obj.kind}/"
                            f"{obj.metadata.namespace}/{obj.metadata.name}"
                        ),
                    }
                force = _force_requested(qs)
                self._count("update")
                # oplint: disable=RMW001 — HTTP router, not a RMW loop: the
                # GET branch above and this PUT serve DISTINCT client verbs;
                # the rv precondition travels inside the client's object
                return 200, {"object": encode(self.backing.update(obj, force=force))}
            if method == "DELETE":
                self._count("delete")
                return 200, {"object": encode(self.backing.delete(kind, namespace, name))}
        if method == "PATCH" and len(rest) in (3, 4):
            kind, namespace, name = rest[:3]
            subresource = rest[3] if len(rest) == 4 else None
            self._count("patch")
            obj = self.backing.patch(
                kind, namespace, name, body.get("patch"),
                subresource=subresource,
            )
            return 200, {"object": encode(obj)}
        return 404, {"error": "NotFound", "message": "bad objects route"}

    def _handle_patch_batch(
        self, body: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        """One request, many merge-patches (the agent-tick verb: Node
        heartbeat + every dirty pod mirror in a single round-trip). Items
        apply in order, each atomic on its own; per-item errors come back
        in-band so one missing pod can't fail the heartbeat riding next
        to it."""
        items = body.get("items")
        if not isinstance(items, list):
            return 400, {"error": "BadPatch", "message": "items must be a list"}
        self._count("patch_batch")
        results = []
        # ONE source of truth for batch semantics: the same loop the
        # in-process backends run (item validation, error-to-value mapping)
        # — only the wire encoding and counters are HTTP concerns
        for val in patch_batch_via_loop(self.backing, items):
            if isinstance(val, Exception):
                if isinstance(val, Conflict):
                    self._count("conflict")
                results.append(
                    {"error": type(val).__name__, "message": str(val)}
                )
            else:
                # patch_item, NOT patch: "patch" counts REQUESTS (the
                # round-trips the verb exists to collapse); items ride one
                # patch_batch request and are tallied separately
                self._count("patch_item")
                results.append({"object": encode(val)})
        return 200, {"results": results}

    def _handle_watch(self, qs: Dict[str, List[str]]) -> Tuple[int, Dict[str, Any]]:
        try:
            after = int(qs.get("after", ["-1"])[0])
            timeout = min(float(qs.get("timeout", ["25"])[0]), 55.0)
            resume_rv = qs.get("resource_version", [None])[0]
            resume_rv = int(resume_rv) if resume_rv is not None else None
        except ValueError as e:
            # malformed query from a skewed client: a 400, not an opaque 500
            # (same posture as the selector parameter above)
            return 400, {"error": "BadRequest", "message": f"bad watch param: {e}"}
        self._count("watch")
        client_instance = qs.get("instance", [self.instance])[0]
        if after < 0:
            if resume_rv is not None:
                # rv-anchored (re)registration: a client (typically an
                # informer cache) that has observed everything up to
                # resume_rv asks for the tail — replayed from the ring when
                # provable, relist otherwise (the 410 Gone fallback)
                return 200, self._resume_or_relist(resume_rv)
            # registration: hand the current head so the client sees only
            # post-registration events (ObjectStore watch semantics); the
            # barrier makes sure already-committed events are in the log
            # before the head is read (see _RegistrationBarrier)
            barrier = _RegistrationBarrier()
            self._watch_q.put(barrier)
            barrier.reached.wait(timeout=5.0)
            return 200, {
                "events": [], "next": self._log.head,
                "instance": self.instance,
            }
        if client_instance != self.instance:
            # cursor from a previous incarnation: its seqs mean nothing in
            # this log (even if numerically <= head) — but the client's rv
            # anchor is backed by the DURABLE store sequence, so a restarted
            # server can often resume a caught-up client without a relist
            return 200, self._resume_or_relist(resume_rv)
        events, head = self._log.read_after(after, timeout)
        if events is None:
            # cursor fell off the window → rv resume or relist ('rv too old')
            return 200, self._resume_or_relist(resume_rv)
        return 200, self._watch_payload(events, head)

    def _watch_payload(self, events: List[Tuple], next_seq: int) -> Any:
        """A watch response for ``events``. Preencoded path (default):
        byte-join each event's cached wire segment — the ONE json.dumps
        per event already ran at append, so serving N watchers costs N
        byte-joins, not N re-encodes (O(events) fan-out). Legacy path
        (``preencode=False``, the A/B bench baseline): rebuild the wire
        dict and json.dumps the whole payload per watcher — the
        O(watchers×events) shape this round removed."""
        if self._log.preencode and all(
            len(e) > 7 and e[7] is not None for e in events
        ):
            return _Preencoded(
                prefix=b'{"events":[',
                segments=[e[7] for e in events],
                suffix=b'],"next":%d,"instance":"%s"}'
                       % (next_seq, self.instance.encode()),
            )
        t0 = time.perf_counter()
        body = json.dumps({
            "events": [_event_wire(e) for e in events],
            "next": next_seq,
            "instance": self.instance,
        }).encode()
        _note_watch_encode(
            time.perf_counter() - t0,
            events=len(events), payloads=1, nbytes=len(body),
        )
        return _Preencoded(body=body)

    def _resume_or_relist(self, resume_rv: Optional[int]) -> Dict[str, Any]:
        """Serve an rv-anchored resume from the event ring when the ring
        provably retains every event past ``resume_rv``; otherwise fall back
        to a full relist (≙ kube's 410 Gone → relist)."""
        if resume_rv is not None:
            events = self._log.resume_after_rv(resume_rv)
            if events is not None:
                return self._watch_payload(
                    events, events[-1][0] if events else self._log.head
                )
        return self._relist_payload()

    def _relist_payload(self) -> Dict[str, Any]:
        # capture the cursor BEFORE listing: an event appended during the
        # list then replays after the relist (benign for level-triggered
        # consumers) instead of being skipped (lost update) — the same
        # ordering SqliteStore._poll_loop uses for its gap recovery
        self._count("relist")
        head = self._log.head
        watermark = self._log.watermark_rv()
        objs = []
        for kind in _all_kinds():
            objs.extend(encode(o) for o in self.backing.list(kind))
        return {
            "relist": objs, "next": head, "instance": self.instance,
            "rv": watermark,
        }


def _all_kinds() -> List[str]:
    from mpi_operator_tpu.machinery.serialize import KIND_CLASSES

    return list(KIND_CLASSES)


def servable_routes() -> List[str]:
    """Every ``"METHOD /route-pattern"`` the router above dispatches — THE
    introspection seam analysis/authzcheck.py diffs authz_policy.json
    against, so a new endpoint that ships without a declared authorization
    posture is a checker finding, not a silent hole. Placeholder segments
    (``{kind}`` etc.) stand for the object-path wildcards ``_handle_objects``
    consumes positionally; the peer RPC fan-out is enumerated from the SAME
    ``_PEER_ROUTE_METHODS`` table ``_handle_replica`` dispatches from, so
    the two can never drift."""
    routes = [
        "GET /healthz",
        "GET /v1/replica/status",
        "GET /v1/watch",
        "POST /v1/patch-batch",
        "POST /v1/objects",
        "GET /v1/objects/{kind}",
        "GET /v1/objects/{kind}/{ns}/{name}",
        "PUT /v1/objects/{kind}/{ns}/{name}",
        "DELETE /v1/objects/{kind}/{ns}/{name}",
        "PATCH /v1/objects/{kind}/{ns}/{name}",
        "PATCH /v1/objects/{kind}/{ns}/{name}/{subresource}",
    ]
    routes.extend(
        "POST /v1/replica/" + wire
        for wire in sorted(StoreServer._PEER_ROUTE_METHODS)
    )
    return routes


def _event_wire(e: Tuple) -> Dict[str, Any]:
    """One _EventLog entry as its wire dict. ``trace``/``ts`` ship only
    when the originating write was traced — old clients ignore the keys,
    new clients against old servers read their absence as 'no link'."""
    s, t, k, d, rv = e[0], e[1], e[2], e[3], e[4]
    out = {"seq": s, "type": t, "kind": k, "object": d, "rv": rv}
    origin = e[5] if len(e) > 5 else None
    ts = e[6] if len(e) > 6 else 0.0
    if origin:
        out["trace"] = list(origin)
    if ts:
        out["ts"] = ts
    return out


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class HttpStoreClient:
    """Drop-in store over the network; same duck-typed surface.

    One background long-poll thread serves every local watcher (the same
    single-poller pattern as SqliteStore). ≙ the generated clientset +
    shared informer factory pair of the reference
    (v2/pkg/client/, mpi_job_controller.go:300-339).

    **Replica awareness**: ``url`` may be a list (or comma-joined string)
    of replica endpoints. A connection-refused request rotates to the
    next endpoint BEFORE backing off (one dead replica costs a
    re-connect, not a backoff window), and a 421 NotLeader answer is
    followed to the advertised leader (bounded redirects, learning the
    endpoint if it was not in the list) — so follower reads spread over
    the list while mutations find the leased leader on their own.
    """

    def __init__(self, url, *, timeout: float = 10.0,
                 watch_poll_timeout: float = 25.0,
                 token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 conn_refused_retries: int = 5,
                 retry_base_delay: float = 0.1,
                 not_leader_redirects: int = 3,
                 watch_retry_base: float = 0.5,
                 replication_unavailable_retries: int = 2):
        urls = url.split(",") if isinstance(url, str) else list(url)
        self._endpoints = [u.strip().rstrip("/") for u in urls if u.strip()]
        if not self._endpoints:
            raise ValueError("HttpStoreClient needs at least one endpoint")
        self._ep_lock = threading.Lock()
        self._ep_i = 0
        # `url` stays an attribute (not a property) — the current active
        # endpoint; rotation/redirect move it so the watch long-poll
        # follows the same endpoint choice as the verbs
        self.url = self._endpoints[0]
        self.token = token
        self.timeout = timeout
        self.watch_poll_timeout = watch_poll_timeout
        # bounded retry/backoff across a store restart window (the
        # apiserver-HA resilience the reference gets for free,
        # proposals/scalable-robust-operator.md:90-113): a CONNECTION-
        # REFUSED request never reached the server, so replaying it is
        # safe for every verb — rv-guarded PUT/PATCH would 409 on a
        # phantom duplicate anyway. Default 5 retries, 0.1s doubling to a
        # 2s cap (~3s window) rides out a quick restart without turning a
        # hard outage into a hang. 0 disables. The backoff is JITTERED
        # (up to +25%) so a fleet of clients losing one replica does not
        # re-dial the next in lockstep.
        self.conn_refused_retries = conn_refused_retries
        self.retry_base_delay = retry_base_delay
        self.not_leader_redirects = not_leader_redirects
        # a 503 ReplicationUnavailable is INDETERMINATE (the leader lost
        # its majority mid-ship), NOT a routing error: the client retries
        # with backoff on the SAME endpoint — rotating would park it on a
        # follower whose 421 just points back (a redirect loop) and whose
        # lagging read could miss the maybe-committed write. By protocol
        # the 503 sender has stepped down, so the retry resolves through
        # its 421 hint to the new leader, where rv/uid preconditions turn
        # a survived first attempt into a typed Conflict/AlreadyExists
        # instead of a silent duplicate. 0 disables (surface immediately).
        self.replication_unavailable_retries = replication_unavailable_retries
        # watch re-poll backoff base: the actual delay is JITTERED per
        # client (see _watch_retry_delay) — N watchers severed together by
        # one server restart must NOT re-poll in lockstep, or every
        # recovery becomes a thundering herd of simultaneous relists
        self.watch_retry_base = watch_retry_base
        self._retry_rng = random.Random(f"{id(self)}:{self._endpoints[0]}")
        # observable by tests/benches: how often each failover path fired
        self.retry_stats = {"conn_refused_retries": 0,
                            "endpoint_rotations": 0,
                            "not_leader_redirects": 0,
                            "replication_unavailable_retries": 0}
        # https:// store with a self-signed cert: pin it (or its CA) here —
        # certificate verification stays ON; we only change the trust root.
        # None = system trust store.
        self._ssl_ctx = None
        if ca_file:
            import ssl

            self._ssl_ctx = ssl.create_default_context(cafile=ca_file)
        self._lock = threading.RLock()
        # serializes watch() poller bootstrap only — see watch() for why the
        # bootstrap request must not ride self._lock
        self._init_lock = threading.Lock()
        self._watchers: List[Tuple[Optional[str], "queue.Queue[WatchEvent]"]] = []
        self._relist_listeners: List = []
        self._poller: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # highest object resource_version observed on the watch: the DURABLE
        # resume anchor. When the seq cursor goes stale (server restart,
        # fell off the event window) the server replays from this rv out of
        # its ring instead of relisting, whenever it can prove completeness.
        self._max_rv = 0

    # -- transport ----------------------------------------------------------

    def _rotate_endpoint(self) -> int:
        """Move to the next endpoint in the list; returns the list
        length so the caller can do per-REQUEST cycle accounting (the
        shared cursor is advanced by every thread — comparing it against
        a per-request start index would let concurrent requests corrupt
        each other's wrap detection into a backoff-free hot spin)."""
        with self._ep_lock:
            n = len(self._endpoints)
            if n > 1:
                self._ep_i = (self._ep_i + 1) % n
                self.url = self._endpoints[self._ep_i]
                self.retry_stats["endpoint_rotations"] += 1
            return n

    def _follow_leader(self, leader: str) -> bool:
        """Adopt a NotLeader hint as the active endpoint, learning it if
        the replica list did not include it (leader discovery). Only a
        dialable URL is adopted — an in-process replica set with no
        advertise mapping hints bare node ids, and parking the client on
        'n0' would poison every subsequent request."""
        leader = leader.rstrip("/")
        if not leader.startswith(("http://", "https://")):
            return False
        with self._ep_lock:
            if leader not in self._endpoints:
                self._endpoints.append(leader)
            self._ep_i = self._endpoints.index(leader)
            self.url = leader
            self.retry_stats["not_leader_redirects"] += 1
        return True

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = None,
    ) -> Dict[str, Any]:
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if data else {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        traceparent = trace.inject()
        if traceparent:
            # propagate the calling span across the wire (W3C shape); the
            # server's store.request span parents on it, stitching the
            # cross-process hop into one trace
            headers[trace.TRACEPARENT_HEADER] = traceparent
        delay = self.retry_base_delay
        attempt = 0
        redirects = 0
        refused_in_cycle = 0
        ru_attempts = 0
        ru_delay = self.retry_base_delay
        while True:
            req = urllib.request.Request(
                self.url + path, data=data, method=method, headers=headers,
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout, context=self._ssl_ctx
                ) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                payload = {}
                try:
                    payload = json.loads(e.read())
                except (ValueError, OSError):
                    pass  # non-JSON error body (proxy page): generic raise below
                cls = _ERROR_CLASSES.get(payload.get("error", ""))
                if cls is NotLeader:
                    leader = payload.get("leader")
                    if (
                        leader
                        and redirects < self.not_leader_redirects
                        and self._follow_leader(leader)
                    ):
                        # DEFINITE rejection (nothing committed): follow
                        # the hint immediately — the common failover case
                        # of a client parked on a follower
                        redirects += 1
                        continue
                    raise NotLeader(payload.get("message", str(e)),
                                    leader=leader) from None
                if cls is ReplicationUnavailable:
                    # indeterminate, not a routing error: retry with
                    # backoff on the SAME endpoint (no rotation — see
                    # __init__). The sender stepped down, so the retry
                    # lands on its 421 hint toward the new leader.
                    if ru_attempts < self.replication_unavailable_retries:
                        ru_attempts += 1
                        self.retry_stats[
                            "replication_unavailable_retries"] += 1
                        jittered = ru_delay * (
                            1 + self._retry_rng.uniform(0, 0.25)
                        )
                        if not self._stop.wait(jittered):
                            ru_delay = min(ru_delay * 2, 2.0)
                            continue
                    raise ReplicationUnavailable(
                        payload.get("message", str(e))
                    ) from None
                if cls is not None:
                    raise cls(payload.get("message", str(e))) from None
                raise
            except urllib.error.URLError as e:
                # connection refused = the request NEVER reached the server
                # (unlike a reset mid-flight, there is nothing ambiguous to
                # replay): rotate to the next replica FIRST — only once
                # every endpoint refused does the bounded backoff fire, so
                # a single dead replica never costs a backoff window. The
                # retry budget counts BACKOFF CYCLES (full wraps of the
                # endpoint list), not individual refusals — charging per
                # refusal would shrink the documented ~3s outage ride-out
                # window N-fold for an N-endpoint client, killing exactly
                # the heartbeating agents the budget exists to protect.
                if not isinstance(e.reason, ConnectionRefusedError):
                    raise
                refused_in_cycle += 1
                if refused_in_cycle >= self._rotate_endpoint():
                    # every endpoint refused within THIS request's cycle
                    refused_in_cycle = 0
                    if attempt >= self.conn_refused_retries:
                        raise
                    attempt += 1
                    self.retry_stats["conn_refused_retries"] += 1
                    jittered = delay * (1 + self._retry_rng.uniform(0, 0.25))
                    if self._stop.wait(jittered):
                        raise  # closing: don't outlive the client
                    delay = min(delay * 2, 2.0)

    def replica_status(self) -> List[Dict[str, Any]]:
        """Per-endpoint /v1/replica/status (best-effort: an unreachable
        replica reports as such instead of failing the survey) — the
        `ctl store status` data source. The survey FOLLOWS each answer's
        ``peers`` hints (node id → advertised URL), so the full
        membership resolves from ANY single endpoint on the command line
        — the operator triaging leader loss should not need all three
        addresses at hand. Discovered rows are marked ``discovered``;
        the probe count is bounded so a corrupt hint map cannot spider.
        The bearer token goes ONLY to operator-configured endpoints —
        peer hints ride an unauthenticated probe, so a compromised
        replica (or an on-path attacker on the plaintext seam) hinting
        an attacker URL can never harvest the admin credential; the
        status route serves without auth anyway except under
        --auth-reads, where an unauthenticated discovered row reads as
        unreachable (add the endpoint to the configured list to probe
        it with credentials)."""
        out: List[Dict[str, Any]] = []
        with self._ep_lock:
            configured = [ep.rstrip("/") for ep in self._endpoints]
        pending = list(configured)
        seen: set = set()
        while pending and len(seen) < 16:
            ep = pending.pop(0).rstrip("/")
            if ep in seen:
                continue
            seen.add(ep)
            headers = {}
            if self.token and ep in configured:
                headers["Authorization"] = f"Bearer {self.token}"
            req = urllib.request.Request(
                ep + "/v1/replica/status", headers=headers,
            )
            row: Dict[str, Any]
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout, context=self._ssl_ctx
                ) as r:
                    row = dict(json.loads(r.read()), endpoint=ep)
            except Exception as e:
                # the survey must render a dead replica, not die with it
                log.debug("replica status probe failed for %s", ep,
                          exc_info=True)
                row = {"endpoint": ep, "role": "unreachable",
                       "error": str(e)}
            if ep not in configured:
                row["discovered"] = True
            out.append(row)
            for hint in (row.get("peers") or {}).values():
                if not isinstance(hint, str) or not hint.startswith(
                    ("http://", "https://")
                ):
                    continue  # in-process sets hint bare node ids
                hint = hint.rstrip("/")
                if hint not in seen and hint not in pending:
                    pending.append(hint)
        return out

    # -- CRUD (same contracts as ObjectStore) -------------------------------

    def create(self, obj: Any) -> Any:
        yield_point("store.create", obj.kind)
        r = self._request(
            "POST", "/v1/objects", {"kind": obj.kind, "object": encode(obj)}
        )
        return decode(obj.kind, r["object"])

    def get(self, kind: str, namespace: str, name: str) -> Any:
        yield_point("store.get", name)
        r = self._request(
            "GET", f"/v1/objects/{kind}/{_quote(namespace)}/{_quote(name)}"
        )
        return decode(kind, r["object"])

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def update(self, obj: Any, force: bool = False) -> Any:
        yield_point("store.put", obj.kind)
        m = obj.metadata
        r = self._request(
            "PUT",
            f"/v1/objects/{obj.kind}/{_quote(m.namespace)}/{_quote(m.name)}"
            + ("?force=1" if force else ""),
            {"object": encode(obj)},
        )
        return decode(obj.kind, r["object"])

    def patch(
        self,
        kind: str,
        namespace: str,
        name: str,
        patch: Any,
        *,
        subresource: Optional[str] = None,
    ) -> Any:
        """Server-side merge-patch: ONE round-trip where the GET+PUT
        optimistic loop needed two-plus (same contract as the other
        backends — rv precondition via metadata.resource_version in the
        patch, status subresource via ``subresource='status'``)."""
        yield_point("store.patch", name)
        path = f"/v1/objects/{kind}/{_quote(namespace)}/{_quote(name)}"
        if subresource:
            path += f"/{_quote(subresource)}"
        r = self._request("PATCH", path, {"patch": patch})
        return decode(kind, r["object"])

    def patch_batch(self, items: List[Dict[str, Any]]) -> List[Any]:
        """Many patches, one request (the agent-tick verb). Same result
        contract as the in-process backends: committed objects in item
        order, per-item failures as exception VALUES."""
        r = self._request(
            "POST", "/v1/patch-batch",
            {"items": [
                {k: v for k, v in it.items() if v is not None}
                for it in items
            ]},
        )
        out: List[Any] = []
        for it, res in zip(items, r.get("results", [])):
            if "object" in res:
                out.append(decode(it["kind"], res["object"]))
            else:
                cls = _ERROR_CLASSES.get(res.get("error", ""), RuntimeError)
                out.append(cls(res.get("message", "")))
        return out

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        yield_point("store.delete", name)
        r = self._request(
            "DELETE", f"/v1/objects/{kind}/{_quote(namespace)}/{_quote(name)}"
        )
        return decode(kind, r["object"])

    def try_delete(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except NotFound:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        yield_point("store.list", kind)
        qs = {}
        if namespace is not None:
            qs["namespace"] = namespace
        if selector:
            qs["selector"] = json.dumps(selector, sort_keys=True)
        path = f"/v1/objects/{kind}"
        if qs:
            path += "?" + urllib.parse.urlencode(qs)
        r = self._request("GET", path)
        return [decode(kind, d) for d in r["objects"]]

    # -- watch --------------------------------------------------------------

    def watch(self, kind: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        # bootstrap serialization is a SEPARATE lock: the cursor-registration
        # request must never run under self._lock (LCK001 — stop_watch and
        # the poll loop's fan-out snapshot would block behind the network for
        # up to the full request timeout). _init_lock is uncontended once the
        # poller exists and nothing else ever takes it, so holding it across
        # the one bootstrap round-trip blocks no hot path.
        with self._init_lock:
            with self._lock:
                if self._poller is not None:
                    self._watchers.append((kind, q))
                    return q
            # register with the server BEFORE adding the local queue: if
            # the request fails, the caller retries with nothing leaked
            # (an early-appended queue would collect events forever)
            # oplint: disable=LCK001 — _init_lock exists solely to
            # serialize this one bootstrap round-trip; nothing else ever
            # takes it, so no hot path can block behind the network here
            r = self._request("GET", "/v1/watch?after=-1")
            with self._lock:
                self._cursor = r["next"]
                self._instance = r.get("instance", "")
                # append and start under ONE lock acquisition: the poller's
                # first watcher snapshot must be guaranteed to include this
                # queue, or an event landing during the gap would fan out to
                # nobody while the cursor advances past it (a lost event)
                self._watchers.append((kind, q))
                self._poller = threading.Thread(
                    target=self._poll_loop, name="http-store-watch",
                    daemon=True,
                )
                self._poller.start()
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            self._watchers = [(k, w) for (k, w) in self._watchers if w is not q]

    def add_relist_listener(self, cb) -> None:
        """Register ``cb(objects)``: invoked on the poll thread, in event
        order, with the full live-object snapshot whenever the watch had to
        relist. Informer caches require this — a relist's MODIFIED stream
        cannot express deletions that happened inside the gap, so the cache
        replaces its world from the snapshot instead (same contract as
        SqliteStore.add_relist_listener)."""
        with self._lock:
            self._relist_listeners.append(cb)

    def _watch_retry_delay(self) -> float:
        """Jittered watch re-poll backoff in [0.5, 1.5] × the base: N
        clients severed by the same server restart spread their resume
        polls across a full base-width window instead of stampeding the
        just-recovered server in lockstep (each resume can be a relist —
        the single most expensive read the server serves). Seeded per
        client instance, so the spread is deterministic within a process
        (pinned by the spread test in tests/test_http_store.py)."""
        return self.watch_retry_base * (0.5 + self._retry_rng.uniform(0, 1.0))

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                r = self._request(
                    "GET",
                    f"/v1/watch?after={self._cursor}"
                    f"&timeout={self.watch_poll_timeout}"
                    f"&instance={self._instance}"
                    + (f"&resource_version={self._max_rv}"
                       if self._max_rv else ""),
                    timeout=self.watch_poll_timeout + self.timeout,
                )
            except Exception:
                # server briefly unreachable (restart, network): informer
                # backoff-and-retry, cursor preserved; on reconnect the rv
                # anchor above lets a restarted server REPLAY the gap from
                # its ring when provable — the relist is the fallback, not
                # the first resort
                log.debug("watch poll failed; retrying", exc_info=True)
                if self._stop.wait(self._watch_retry_delay()):
                    return
                continue
            try:
                with self._lock:
                    watchers = list(self._watchers)
                    listeners = list(self._relist_listeners)
                if "relist" in r:
                    objs = []
                    for d in r["relist"]:
                        obj = self._decode_event(d)
                        if obj is not None:
                            objs.append(obj)
                    # listeners first: a cache's world-replacement must
                    # precede the per-object MODIFIED replay it subsumes
                    for cb in listeners:
                        try:
                            cb([o.deepcopy() for o in objs])
                        except Exception:
                            # a broken listener must not kill the poll — but
                            # a silently dead informer is a debugging black
                            # hole (EXC001)
                            log.exception("relist listener failed")
                    for obj in objs:
                        self._fan_out(watchers, MODIFIED, obj)
                    # cursor and instance move together, only after the
                    # relist fully lands: adopting the new instance id with
                    # the old cursor would satisfy the server's instance
                    # check and silently skip everything before the cursor
                    self._cursor = r["next"]
                    self._instance = r.get("instance", self._instance)
                    # ADOPT the relist watermark, never max() with the old
                    # anchor: after an rv-space reset (restarted in-memory
                    # backing) the stale higher anchor would later satisfy a
                    # resume in the NEW space and silently skip the events
                    # (deletions included) between the client's true
                    # knowledge and the stale number
                    self._max_rv = r.get("rv", 0)
                    continue
                for ev in r["events"]:
                    self._cursor = ev["seq"]
                    self._max_rv = max(self._max_rv, ev.get("rv", 0))
                    obj = self._decode_event(ev["object"], ev["kind"])
                    if obj is not None:
                        self._fan_out(watchers, ev["type"], obj,
                                      ev.get("trace"), ev.get("ts", 0.0))
                # adopt the response's cursor/instance only once the whole
                # batch landed: an empty rv-anchored resume from a restarted
                # server moves the seq cursor into the NEW incarnation's
                # space without any event to carry it
                self._cursor = r.get("next", self._cursor)
                self._instance = r.get("instance", self._instance)
            except Exception:
                # malformed response (proxy interposing, version skew): a
                # dead poll thread would silently stall every watcher
                # forever — back off and retry instead, same as unreachable
                log.debug("malformed watch response; retrying", exc_info=True)
                if self._stop.wait(self._watch_retry_delay()):
                    return

    @staticmethod
    def _decode_event(data: Dict[str, Any], kind: Optional[str] = None):
        try:
            return decode(kind or data.get("kind"), data)
        except Exception:
            # unknown kind / skewed shape from a newer server — skip the
            # object rather than abort the whole batch
            log.debug("skipping undecodable watch object", exc_info=True)
            return None

    @staticmethod
    def _fan_out(watchers, etype: str, obj, origin=None, ts: float = 0.0
                 ) -> None:
        yield_point("store.watch-deliver", obj.kind)
        if isinstance(origin, list):
            origin = tuple(origin)  # wire shape → the (tid, sid) tuple
        for want, wq in watchers:
            if want is None or want == obj.kind:
                wq.put(WatchEvent(etype, obj.kind, obj.deepcopy(),
                                  origin, ts))

    def close(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=2.0)


# ---------------------------------------------------------------------------
# standalone entry point (the etcd-equivalent process)
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-store", description="Serve a TPUJob object store over HTTP."
    )
    ap.add_argument("--store", default="memory",
                    help="'memory' or 'sqlite:PATH' backing store")
    ap.add_argument("--listen", default="127.0.0.1:8475",
                    help="host:port to bind")
    ap.add_argument("--log-capacity", type=int, default=4096,
                    help="watch event-ring size (events retained for "
                         "?resource_version= resume before clients must "
                         "relist); size it above the burst a lagging "
                         "watcher may miss — a 10k-job storm wants 64k+")
    ap.add_argument("--token-file", default=None,
                    help="file holding the ADMIN bearer token; when set, "
                         "every mutating request must present it")
    ap.add_argument("--read-token-file", default=None,
                    help="file holding a READ-ONLY bearer token: it "
                         "satisfies reads/watches under --auth-reads, and "
                         "mutations presenting it get 403 (the kube "
                         "view-vs-edit role split)")
    ap.add_argument("--agent-tokens-file", default=None,
                    help="file of 'node-name:token' lines: per-agent SCOPED "
                         "credentials (reads + own Node + pods bound to its "
                         "node only — the kubelet credential model); agents "
                         "present theirs via their --token-file")
    ap.add_argument("--auth-reads", action="store_true",
                    help="require a token (any tier) on reads/watches too")
    ap.add_argument("--fair-queue", default=None, metavar="SPEC",
                    help="APF-style per-tenant fair queuing: "
                         "'inflight=16,queue=64,rate=200,burst=400' (any "
                         "subset; rate in req/s per tenant); over-limit "
                         "requests get 429 TooManyRequests")
    ap.add_argument("--quota-file", default=None, metavar="PATH",
                    help='namespace quota admission: JSON {"namespace": '
                         '{"max_jobs": N, "max_chips": M}}; over-quota '
                         "TPUJob creates get a typed 403 QuotaExceeded")
    ap.add_argument("--tls-cert", default=None,
                    help="serve over TLS with this certificate (PEM; "
                         "self-signed acceptable — clients pin it with "
                         "--tls-ca-file)")
    ap.add_argument("--tls-key", default=None,
                    help="private key for --tls-cert (PEM; omit when the "
                         "cert file bundles the key)")
    ap.add_argument("--replica-id", default=None, metavar="ID",
                    help="run as ONE member of a wire-replicated set "
                         "(requires --store sqlite: and --peers/"
                         "--peer-token-file); this process elects, ships "
                         "the log, and serves reads locally — mutations "
                         "on a follower answer 421 with the leader hint")
    ap.add_argument("--peers", default=None, metavar="MAP",
                    help="full replica membership as 'id=http://host:port' "
                         "comma list (must include --replica-id); the "
                         "DIAL map for replication RPCs")
    ap.add_argument("--advertise", default=None, metavar="MAP",
                    help="public 'id=url' map clients are hinted at "
                         "(NotLeader redirects, `ctl store status` "
                         "membership discovery); defaults to --peers — "
                         "set it when peers dial through proxies")
    ap.add_argument("--peer-token-file", default=None,
                    help="file holding the PEER bearer token replication "
                         "RPCs authenticate with; required with "
                         "--replica-id, and every /v1/replica/* RPC "
                         "without it is a typed 403 (fail closed)")
    ap.add_argument("--replica-lease-duration", type=float, default=2.0,
                    help="leader lease in seconds (failover takes ~2 "
                         "leases; lower it only for testing)")
    ap.add_argument("--replica-retry-period", type=float, default=0.25,
                    help="seconds between the replica ticker's renew/"
                         "campaign passes")
    ap.add_argument("--replica-seed", type=int, default=0,
                    help="seed for the ticker's campaign jitter (chaos "
                         "harness determinism)")
    ap.add_argument("--monitoring-port", type=int, default=None,
                    help="serve /metrics + /healthz on this port (the "
                         "scrape endpoint the SLO monitor pulls: store "
                         "request latency by verb, replication lag, "
                         "tenant fair-queue counters); default: off")
    args = ap.parse_args(argv)
    if args.tls_key and not args.tls_cert:
        raise SystemExit("error: --tls-key requires --tls-cert")
    # a server process logs its lifecycle (elections, step-downs, snapshot
    # transfers) — the runbook's first stop when a replica misbehaves
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    trace.configure_from_env("store")
    try:
        host, port = parse_listen(args.listen)
    except ValueError as e:
        raise SystemExit(f"error: --listen: {e}") from None
    try:
        token = read_token_file(args.token_file)
        read_token = read_token_file(args.read_token_file)
        agent_tokens = read_agent_tokens_file(args.agent_tokens_file)
        peer_token = read_token_file(args.peer_token_file)
    except (OSError, ValueError) as e:
        raise SystemExit(f"error: token file: {e}") from None
    ticker = None
    if args.replica_id:
        # the wire-replicated shape: this process is ONE replica-set
        # member; its backing is a ReplicaNode over an HTTP peer fabric
        if not args.store.startswith("sqlite:"):
            raise SystemExit(
                "error: --replica-id requires --store sqlite:PATH (the "
                "replication log rides the sqlite commit seam)"
            )
        if not args.peers:
            raise SystemExit("error: --replica-id requires --peers")
        if peer_token is None:
            raise SystemExit(
                "error: --replica-id requires --peer-token-file "
                "(peer RPCs fail closed without a replication identity)"
            )
        from mpi_operator_tpu.machinery.replica_wire import (
            build_wire_replica,
            parse_peer_map,
        )

        try:
            peers = parse_peer_map(args.peers)
            advertise = (parse_peer_map(args.advertise, "--advertise")
                         if args.advertise else None)
            backing, ticker = build_wire_replica(
                args.replica_id, args.store[len("sqlite:"):], peers,
                peer_token, advertise=advertise,
                lease_duration=args.replica_lease_duration,
                retry_period=args.replica_retry_period,
                seed=args.replica_seed,
            )
        except ValueError as e:
            raise SystemExit(f"error: {e}") from None
    else:
        if args.peers or args.peer_token_file or args.advertise:
            raise SystemExit(
                "error: --peers/--advertise/--peer-token-file require "
                "--replica-id (a standalone store has no peer seam)"
            )
        from mpi_operator_tpu.opshell.__main__ import build_store

        backing = build_store(args.store)
    from mpi_operator_tpu.machinery.fairqueue import (
        load_quota_file,
        parse_fair_queue,
    )

    try:
        fairness = parse_fair_queue(args.fair_queue)
        quota = load_quota_file(args.quota_file)
    except (OSError, ValueError) as e:
        raise SystemExit(f"error: {e}") from None
    if args.auth_reads and token is None:
        raise SystemExit("error: --auth-reads requires --token-file")
    if (read_token is not None or agent_tokens) and token is None:
        raise SystemExit("error: --read-token-file/--agent-tokens-file "
                         "require --token-file (the admin tier anchors auth)")
    server = StoreServer(
        backing, host, port, token=token,
        log_capacity=args.log_capacity,
        # a read tier with open reads would be meaningless: configuring it
        # implies reads need a token (either tier)
        auth_reads=args.auth_reads or read_token is not None,
        read_token=read_token, agent_tokens=agent_tokens,
        tls_cert=args.tls_cert, tls_key=args.tls_key,
        fairness=fairness, quota=quota, peer_token=peer_token,
    ).start()
    ops = None
    if args.monitoring_port is not None:
        from mpi_operator_tpu.opshell.server import OpsServer

        ops = OpsServer(args.monitoring_port)
        ops.start()
        logging.info("metrics on :%d/metrics", ops.port)
    if ticker is not None:
        # the server must be listening BEFORE the ticker campaigns: a
        # won election heartbeats every peer immediately
        ticker.start()
        print(f"replica {args.replica_id} serving on {server.url}",
              flush=True)
    else:
        print(f"store serving on {server.url}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    if ticker is not None:
        ticker.stop()
    if ops is not None:
        ops.stop()
    server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
