"""Rate-limited deduplicating work queue.

≙ client-go's workqueue.RateLimitingInterface as used by the reference
controller (queue wiring at v2/pkg/controller/mpi_job_controller.go:229-234,
drain loop processNextWorkItem :381-438). Semantics preserved:

- **Dedup**: adding a key already queued (or dirty while processing) coalesces;
  a key re-added while being processed is re-queued after done().
- **Rate limiting**: per-key exponential backoff (base 5ms, cap 1000s — the
  client-go defaults) via add_rate_limited(); forget() resets the failure
  count, ≙ the Forget/AddRateLimited pair in processNextWorkItem.
- **Shutdown**: get() returns None after shutdown and the queue drains.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set

from mpi_operator_tpu.machinery.yieldpoints import yield_point


class RateLimitingQueue:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[str] = []
        self._dirty: Set[str] = set()
        self._processing: Set[str] = set()
        self._failures: Dict[str, int] = {}
        self._shutdown = False
        self._base = base_delay
        self._cap = max_delay
        self._timers: List[threading.Timer] = []

    # -- core (client-go Type) ---------------------------------------------

    def add(self, key: str) -> None:
        yield_point("wq.add", key)
        with self._cond:
            if self._shutdown or key in self._dirty:
                return
            self._dirty.add(key)
            if key not in self._processing:
                self._queue.append(key)
                self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        """Blocks until an item is available; returns None on shutdown or
        timeout. The caller must call done(key) when finished."""
        yield_point("wq.get")
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue and not self._shutdown:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if not self._queue:
                return None  # shutdown
            key = self._queue.pop(0)
            self._dirty.discard(key)
            self._processing.add(key)
            return key

    def done(self, key: str) -> None:
        yield_point("wq.done", key)
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty and key not in self._queue:
                self._queue.append(key)
                self._cond.notify()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- rate limiting ------------------------------------------------------

    def num_requeues(self, key: str) -> int:
        with self._lock:
            return self._failures.get(key, 0)

    def add_rate_limited(self, key: str) -> None:
        with self._lock:
            n = self._failures.get(key, 0)
            self._failures[key] = n + 1
            delay = min(self._base * (2**n), self._cap)
        self.add_after(key, delay)

    def forget(self, key: str) -> None:
        with self._lock:
            self._failures.pop(key, None)

    def add_after(self, key: str, delay: float) -> None:
        if delay <= 0:
            self.add(key)
            return
        t = threading.Timer(delay, self.add, args=(key,))
        t.daemon = True
        with self._lock:
            if self._shutdown:
                return
            self._timers.append(t)
            self._timers = [x for x in self._timers if x.is_alive() or not x.finished.is_set()]
        t.start()

    # -- lifecycle ----------------------------------------------------------

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            for t in self._timers:
                t.cancel()
            self._timers.clear()
            self._cond.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._lock:
            return self._shutdown
